"""The pjit training core: sharded state init + compiled train step.

TPU-native replacement for the reference's (unspecified) PS pull/push hot loop
(SURVEY.md §3.4): one ``jax.jit``-compiled step over an explicit
``jax.sharding.Mesh``; GSPMD inserts the gradient ``psum`` (and any FSDP
all-gather/reduce-scatter) over ICI. The Trainer is model-agnostic: it takes
pure functions (``init_fn``, ``loss_fn``) and never inspects model internals,
so the elastic master can rebuild it at a new world size from the same
functions and rules.

Design notes (TPU):
- parameters/optimizer state stay fp32; compute casts to bf16 (MXU-native)
  via :func:`cast_floating` inside the loss.
- gradient accumulation is a ``lax.scan`` over microbatches — static trip
  count, no Python loop in the traced step.
- state is donated, so buffers are reused in place (HBM headroom).
- flax ``Partitioned`` metadata boxes are kept in the state; logical-axis
  rules map them to mesh axes (see :mod:`easydl_tpu.core.sharding`).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from easydl_tpu.core import sharding as shd
from easydl_tpu.core.mesh import MeshSpec, build_mesh
from easydl_tpu.utils.logging import get_logger

log = get_logger("core", "trainer")

LossFn = Callable[..., Tuple[jax.Array, Dict[str, jax.Array]]]
InitFn = Callable[[jax.Array], Any]


def cast_floating(tree: Any, dtype: jnp.dtype) -> Any:
    """Cast floating-point leaves (keeps integer/bool leaves intact)."""

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array

    @property
    def int_step(self) -> int:
        return int(jax.device_get(self.step))


@dataclass
class TrainConfig:
    global_batch: int = 32
    grad_accum: int = 1
    #: lax.scan unroll for the accumulation loop. The profiler trace
    #: (scripts/bench_profile.py → PROFILE.json) showed the scan carry's
    #: gradient adds as dynamic-update-slice fusions costing ~16% of the
    #: step at accum 32; unrolling lets XLA fuse the carry update across
    #: ``accum_unroll`` microbatches, cutting that HBM write traffic.
    accum_unroll: int = 1
    compute_dtype: Any = jnp.bfloat16
    seed: int = 0
    rules: Sequence[Tuple[str, Any]] = field(default_factory=lambda: shd.DEFAULT_RULES)
    donate_state: bool = True

    def __post_init__(self) -> None:
        if self.global_batch % max(self.grad_accum, 1):
            raise ValueError(
                f"global_batch={self.global_batch} not divisible by grad_accum={self.grad_accum}"
            )


class Trainer:
    """Builds and runs the compiled train step on a mesh.

    Args:
      init_fn: ``rng -> params`` (flax ``Partitioned`` boxes welcome).
      loss_fn: ``(params, batch, rng) -> (loss, aux_metrics)``. Called with
        params cast to ``config.compute_dtype``.
      optimizer: an optax ``GradientTransformation``.
      mesh: an existing Mesh, or None to build one from ``mesh_spec``.
    """

    def __init__(
        self,
        init_fn: InitFn,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        config: TrainConfig,
        mesh: Optional[Mesh] = None,
        mesh_spec: Optional[MeshSpec] = None,
    ):
        self.config = config
        self.mesh = mesh if mesh is not None else build_mesh(mesh_spec or MeshSpec())
        self.init_fn = init_fn
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._state_shardings: Any = None
        self._step_fn = None
        self._abstract: Any = None

    # ------------------------------------------------------------------ init
    def _abstract_state(self) -> TrainState:
        def make(rng):
            params = self.init_fn(rng)
            opt_state = self.optimizer.init(params)
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=opt_state,
                rng=rng,
            )

        # Old-style uint32 PRNG keys: checkpointable as plain arrays.
        rng = jax.random.PRNGKey(self.config.seed)
        if self._abstract is None:  # eval_shape re-traces init+opt: cache it
            self._abstract = jax.eval_shape(make, rng)
        return self._abstract, make, rng

    def state_shardings(self) -> Any:
        if self._state_shardings is None:
            abstract, _, _ = self._abstract_state()
            self._state_shardings = shd.state_shardings(
                abstract, self.mesh, self.config.rules
            )
        return self._state_shardings

    def init_state(self) -> TrainState:
        """Shard-aware init: the jit's out_shardings place every parameter
        shard directly on its device — no host-side full materialisation."""
        abstract, make, rng = self._abstract_state()
        shardings = self.state_shardings()
        t0 = time.perf_counter()
        state = jax.jit(make, out_shardings=shardings)(rng)
        log.info(
            "initialised state on mesh [%s] in %.2fs (%d params)",
            ", ".join(f"{k}={v}" for k, v in self.mesh.shape.items() if v > 1) or "1 device",
            time.perf_counter() - t0,
            sum(x.size for x in jax.tree.leaves(shd.unbox(abstract.params))),
        )
        return state

    def abstract_state(self) -> TrainState:
        """Shape/dtype tree of the state (no allocation) — what checkpoint
        restore matches leaves against."""
        return self._abstract_state()[0]

    def restore_from(self, checkpoint, step: Optional[int] = None) -> TrainState:
        """Restore ``step`` (default: latest) from a CheckpointManager onto
        THIS trainer's mesh — the save may have used any other mesh shape
        (reshard-on-restore). The single public entry for resuming: the
        elastic worker, the evaluator, and the zoo runner all come through
        here."""
        if step is None:
            step = checkpoint.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {checkpoint.directory}"
                )
        return checkpoint.restore(step, self.abstract_state(), self.state_shardings())

    # ------------------------------------------------------------------ step
    def _build_step(self):
        accum = max(self.config.grad_accum, 1)
        compute_dtype = self.config.compute_dtype
        loss_fn = self.loss_fn
        optimizer = self.optimizer

        def forward(params, batch, rng):
            loss, aux = loss_fn(cast_floating(params, compute_dtype), batch, rng)
            return loss.astype(jnp.float32), aux

        grad_fn = jax.value_and_grad(forward, has_aux=True)

        def single(params, batch, rng):
            (loss, aux), grads = grad_fn(params, batch, rng)
            return loss, aux, grads

        def accumulated(params, batch, rng):
            # [global, ...] -> [accum, global/accum, ...]
            def split(x):
                return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

            microbatches = jax.tree.map(split, batch)

            def body(carry, xs):
                loss_sum, aux_sum, grad_sum = carry
                mb, i = xs
                loss, aux, grads = single(params, mb, jax.random.fold_in(rng, i))
                return (
                    loss_sum + loss,
                    jax.tree.map(jnp.add, aux_sum, aux),
                    jax.tree.map(jnp.add, grad_sum, grads),
                ), None

            loss0, aux0, grads0 = single(
                params, jax.tree.map(lambda x: x[0], microbatches), jax.random.fold_in(rng, 0)
            )
            rest = jax.tree.map(lambda x: x[1:], microbatches)
            (loss_sum, aux_sum, grad_sum), _ = jax.lax.scan(
                body, (loss0, aux0, grads0), (rest, jnp.arange(1, accum)),
                unroll=max(self.config.accum_unroll, 1),
            )
            scale = 1.0 / accum
            return (
                loss_sum * scale,
                jax.tree.map(lambda a: a * scale, aux_sum),
                jax.tree.map(lambda g: g * scale, grad_sum),
            )

        def train_step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, jax.Array]]:
            step_rng = jax.random.fold_in(state.rng, state.step)
            if accum > 1:
                loss, aux, grads = accumulated(state.params, batch, step_rng)
            else:
                loss, aux, grads = single(state.params, batch, step_rng)
            updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            metrics = {
                "loss": loss,
                "grad_norm": optax.global_norm(grads),
                **aux,
            }
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
            )
            return new_state, metrics

        shardings = self.state_shardings()
        batch_shd = shd.batch_sharding(self.mesh)
        replicated = NamedSharding(self.mesh, P())
        return jax.jit(
            train_step,
            in_shardings=(shardings, batch_shd),
            out_shardings=(shardings, replicated),
            donate_argnums=(0,) if self.config.donate_state else (),
        )

    @property
    def step_fn(self):
        if self._step_fn is None:
            self._step_fn = self._build_step()
        return self._step_fn

    def shard_batch(self, host_batch: Any) -> Any:
        """Place a host (numpy) batch onto the mesh, batch-sharded."""
        sharding_ = shd.batch_sharding(self.mesh)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sharding_, x), host_batch
        )

    def train_step(self, state: TrainState, host_batch: Any):
        return self.step_fn(state, self.shard_batch(host_batch))

    # ------------------------------------------------------------------ eval
    def build_eval_step(self, eval_fn: LossFn):
        """Compile an eval step (no grads, no donation)."""
        compute_dtype = self.config.compute_dtype

        def eval_step(state: TrainState, batch):
            _, aux = eval_fn(cast_floating(state.params, compute_dtype), batch, state.rng)
            return aux

        return jax.jit(
            eval_step,
            in_shardings=(self.state_shardings(), shd.batch_sharding(self.mesh)),
            out_shardings=NamedSharding(self.mesh, P()),
        )
