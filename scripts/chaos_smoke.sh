#!/usr/bin/env bash
# Chaos smoke: the fastest deterministic drills as a single command —
# worker SIGKILL (data-plane recovery), master crash/failover
# (control-plane recovery), and the PS zero-loss drill (shard SIGKILL
# mid-push-storm; rescue must replay the push WAL to bit-identical table
# state) — the pre-merge sanity gate for changes that touch the
# elastic/recovery path. The full catalog (heartbeat loss, RPC burst,
# checkpoint corruption, mid-drain failover, zombie writer) runs via
#   python scripts/chaos_run.py
# and as `pytest -m chaos` (the slow-marked e2e tests).
#
# After the drills, each kept workdir is folded into a Perfetto trace by
# scripts/trace_export.py; an empty or unparseable merged trace FAILS the
# smoke — export rot is caught in-tree, next to the drills that feed it.
# The zero-loss verdict must additionally record at least one replayed WAL
# record: a "pass" where the rescue never consumed the log would only
# prove the kill missed the window, and the smoke refuses to count it.
# The reshard-under-fire verdict gets the same treatment: it must record
# at least one completed row migration AND at least one mid-migration WAL
# tail push replayed onto a destination — a "pass" where the cutover beat
# every in-flight push would never have exercised the tail-replay path.
# The cell-failover verdict likewise: at least one shipped WAL segment
# replayed on the standby, every fenced late push refused, and digest
# parity against the acked ledger — else the cross-cell path never ran.
# The beyond-RAM tier drill demands real spill evidence on top of the
# zero-loss gates: thousands of cold (mmap-spilled) rows, at least one
# demotion and one cold hit — else the table fit in its hot arena and
# the "crash + reshard a spilled table" claim is vacuous.
#
# The detection loop (ISSUE 19) gates every drill the same way: a verdict
# whose scenario declares an expected alert must carry a PASSING
# detected_and_cleared check (alert fired within the TTD budget, cleared
# after recovery, decision ledger byte-replayed); the fault-free control
# must carry no_false_pages with ZERO pages; and the per-drill measured
# TTDs aggregate into DETECT.json via scripts/slo_report.py --detect.
set -euo pipefail
cd "$(dirname "$0")/.."

# Repo-invariant gate FIRST (docs/design/static-analysis.md): the drills
# below assume the disciplines easylint enforces (WAL-then-apply ordering,
# instrumented RPCs, virtual-clock-pure policies) — if those rotted, fail
# in seconds here, not after a seven-minute drill chases the symptom.
python scripts/easylint.py

# Scenario-directory gate (docs/scenarios.md): every scenarios/*.yaml must
# load + validate — a malformed spec fails here in milliseconds, not
# mid-drill. The headline multi_tenant_contention drill below RUNS from
# its YAML (the catalog entry loads it), so this also guards the drill's
# own definition.
python scripts/scenario_run.py --list

LOG=$(mktemp)
trap 'rm -f "$LOG"' EXIT

env JAX_PLATFORMS=cpu python scripts/chaos_run.py \
    --scenario worker_kill --scenario master_crash \
    --scenario ps_shard_crash_zero_loss \
    --scenario ps_reshard_under_fire \
    --scenario ps_tier_beyond_ram \
    --scenario serve_during_reshard \
    --scenario serve_replica_death_mid_flood \
    --scenario trainer_crash_mid_loop \
    --scenario rollout_half_update \
    --scenario retrieval_replica_death_mid_index_update \
    --scenario multi_tenant_contention \
    --scenario cell_failover \
    --scenario fault_free_control --keep-workdir "$@" \
    2>&1 | tee "$LOG"

# Verdict files from THIS run (chaos_run prints "PASS <name> ... -> <path>").
VERDICTS=$(awk '/^(PASS|FAIL) .* -> .*\.json$/{print $NF}' "$LOG")
test -n "$VERDICTS" || { echo "chaos_smoke: no verdicts found" >&2; exit 1; }

for verdict in $VERDICTS; do
    # Detection gate (every drill): a scenario that declares an expected
    # alert must carry a PASSING detected_and_cleared check — a verdict
    # with the expectation but no check means the drill ran without its
    # alerting witness, and the smoke refuses to count it. The fault-free
    # control must carry no_false_pages with ZERO page-severity alerts.
    # Either way the recorded alert-decision ledger must have re-derived
    # byte-identically (replay_identical) — non-reproducible detection is
    # no detection.
    python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
expect = doc.get("expect") or {}
checks = (doc.get("invariants") or {}).get("checks") or {}
if expect.get("detect"):
    det = checks.get("detected_and_cleared")
    assert det is not None, (
        f"{sys.argv[1]}: scenario declares expect.detect but the verdict "
        "carries NO detected_and_cleared check — the drill ran without "
        "its alerting witness, the detection claim is vacuous")
    assert det.get("ok"), (
        f"{sys.argv[1]}: detected_and_cleared FAILED: {det}")
    assert det.get("replay_identical") and det.get("replay_decisions", 0) > 0, (
        f"{sys.argv[1]}: alert decision ledger did not byte-replay: {det}")
    print(f"detect OK: {det['alert']} fired ttd={det['ttd_s']}s "
          f"(budget {det['ttd_budget_s']}s), cleared, "
          f"{det['replay_decisions']} decisions byte-replayed")
if expect.get("detect_none"):
    ctl = checks.get("no_false_pages")
    assert ctl is not None, (
        f"{sys.argv[1]}: fault-free control carries NO no_false_pages "
        "check — the negative control never armed its witness")
    assert ctl.get("ok") and not ctl.get("pages_fired"), (
        f"{sys.argv[1]}: the fault-free control PAGED: {ctl}")
    assert ctl.get("replay_identical") and ctl.get("replay_decisions", 0) > 0, (
        f"{sys.argv[1]}: control alert ledger did not byte-replay: {ctl}")
    print(f"control OK: {ctl['rounds']} rounds, ZERO pages, "
          f"{ctl['replay_decisions']} decisions byte-replayed")
PY
    case "$verdict" in
    *ps_shard_crash_zero_loss*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
replayed = doc["zero_loss"]["counters"].get("wal_replayed_records", 0)
assert replayed >= 1, (
    f"{sys.argv[1]}: zero-loss verdict shows {replayed} WAL records "
    "replayed — the rescue never exercised the log, the pass is vacuous")
print(f"zero-loss OK: {int(replayed)} WAL records replayed")
PY
        ;;
    *ps_reshard_under_fire*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
resh = doc["zero_loss"]["reshard"]
migrations = resh.get("migrations", [])
rows = sum(m.get("rows_migrated", 0) for m in migrations)
tail = sum(m.get("tail_pushes_replayed", 0) for m in migrations)
assert migrations and rows >= 1, (
    f"{sys.argv[1]}: reshard verdict shows {len(migrations)} migration(s) "
    f"with {rows} rows migrated — no split actually moved data, the pass "
    "is vacuous")
assert tail >= 1, (
    f"{sys.argv[1]}: reshard verdict shows 0 mid-migration WAL tail "
    "pushes replayed — the cutover beat every in-flight push and the "
    "tail-replay path was never exercised")
print(f"reshard OK: {len(migrations)} migration(s), {rows} rows "
      f"migrated, {tail} tail pushes replayed")
PY
        ;;
    *ps_tier_beyond_ram*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
counters = doc["zero_loss"]["counters"]
cold = counters.get("tier_cold_rows", 0)
demotions = counters.get("tier_demotions", 0)
cold_hits = counters.get("tier_cold_hits", 0)
assert cold >= 1000, (
    f"{sys.argv[1]}: only {int(cold)} cold rows at the end of the drill "
    "— the table fit in its hot arena, the kill and the split never "
    "touched a spilled table, the beyond-RAM pass is vacuous")
assert demotions >= 1 and cold_hits >= 1, (
    f"{sys.argv[1]}: {int(demotions)} demotions / {int(cold_hits)} cold "
    "hits — tier maintenance (or cold serving) never ran under fire")
replayed = counters.get("wal_replayed_records", 0)
assert replayed >= 1, (
    f"{sys.argv[1]}: the rescued spilled shard replayed {int(replayed)} "
    "WAL records — the crash never exercised the log")
resh = doc["zero_loss"]["reshard"]
migrations = resh.get("migrations", [])
rows = sum(m.get("rows_migrated", 0) for m in migrations)
assert migrations and rows >= 1, (
    f"{sys.argv[1]}: the live split of the spilled table moved "
    f"{rows} rows — no migration actually ran")
print(f"tier OK: {int(cold)} cold rows ({int(demotions)} demotions, "
      f"{int(cold_hits)} cold hits), {int(replayed)} WAL records "
      f"replayed into the rescue, {rows} rows migrated across tiers")
PY
        ;;
    *serve_during_reshard*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
sv = doc["zero_loss"]["serve"]
stale = sv.get("stale_check") or {}
assert sv.get("requests", 0) >= 50 and sv.get("ok", 0) >= 1, (
    f"{sys.argv[1]}: serving replica answered {sv.get('requests', 0)} "
    "requests — the tier was never under serving load, the pass is "
    "vacuous")
assert sv.get("hard_failures", -1) == 0, (
    f"{sys.argv[1]}: {sv.get('hard_failures')} HARD request failures "
    f"during the live split (samples: {sv.get('failure_samples')})")
assert stale.get("ids_checked", 0) > 0 and stale.get("stale_rows", -1) == 0, (
    f"{sys.argv[1]}: stale-read check examined "
    f"{stale.get('ids_checked', 0)} ids and found "
    f"{stale.get('stale_rows')} stale — the hot cache served rows the "
    "migration or a trainer push had already replaced")
print(f"serve OK: {sv['requests']} requests, 0 hard failures, "
      f"{stale['ids_checked']} ids bit-verified post-split")
PY
        ;;
    *serve_replica_death_mid_flood*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
fl = doc["fleet"]
router = fl.get("router") or {}
hedges = router.get("hedges_fired", 0)
shm = fl.get("shm_client_pulls", 0)
assert hedges >= 1, (
    f"{sys.argv[1]}: ZERO hedges fired — the flood never pushed a "
    "request past the hedge delay, the hedging path was never exercised")
assert shm >= 1, (
    f"{sys.argv[1]}: ZERO shm pulls observed — the replicas never rode "
    "the shared-memory transport, the zero-copy path was never exercised")
assert router.get("ejections", 0) >= 1, (
    f"{sys.argv[1]}: the killed replica was never ejected")
print(f"fleet OK: {fl['requests']} requests, 0 hard failures, "
      f"{hedges} hedges ({router.get('hedges_won', 0)} won), "
      f"{int(shm)} shm pulls, "
      f"{fl['stale_check']['scores_checked']} scores bit-verified")
PY
        ;;
    *multi_tenant_contention*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
t = doc["tenant"]
preempts = [m for m in t["moves"] if m.get("from")]
assert len(preempts) >= 2, (
    f"{sys.argv[1]}: {len(preempts)} preemption(s) actuated — the "
    "contention never forced the arbiter's hand, the pass is vacuous")
drains = t["preempt_drains"]
assert drains and all(not d["worker_alive_at_stop"] and not d["escalated"]
                      for d in drains), (
    f"{sys.argv[1]}: a preempted chip was killed before its drain "
    f"completed (or escalated): {drains}")
assert t["replay"]["identical"], (
    f"{sys.argv[1]}: the arbiter decision log did NOT byte-replay "
    f"offline: {t['replay']['mismatches']}")
jobs = t["jobs"]
assert len(jobs) >= 3 and all(j["digests_match"] for j in jobs.values()), (
    f"{sys.argv[1]}: a tenant's tables diverged from its fault-free "
    f"reference: { {n: j['digests_match'] for n, j in jobs.items()} }")
pushes = sum(j["storm"]["pushes"] for j in jobs.values())
print(f"tenant OK: {len(preempts)} preemptions (all drained first), "
      f"{t['replay']['decisions']} decisions byte-replayed, "
      f"{len(jobs)} jobs x digest parity, {pushes} pushes, 0 hard "
      "failures")
PY
        ;;
    *cell_failover*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
c = doc["cell"]
replayed = c.get("replayed_beyond_snapshot", 0)
segs = (c.get("ship") or {}).get("segments_completed", 0)
assert segs >= 1 and replayed >= 1, (
    f"{sys.argv[1]}: {segs} shipped segment(s) and {replayed} shipped "
    "sub-pushes replayed on the standby — the WAL shipping path was "
    "never exercised, the pass is vacuous")
probes = c.get("fence_probes") or []
refused = [p for p in probes if p.get("probe_rejected_stale_epoch")]
assert probes and len(refused) == len(probes), (
    f"{sys.argv[1]}: {len(refused)}/{len(probes)} fenced late pushes "
    "refused — a partitioned old primary could still write into the "
    "promoted lineage")
assert doc.get("digests_match") and c.get("prefix_ok"), (
    f"{sys.argv[1]}: the promoted tier diverged from the acked-push "
    "ledger (prefix_ok="
    f"{c.get('prefix_ok')}, digests_match={doc.get('digests_match')})")
lost = (c.get("rpo") or {}).get("lost_total", -1)
rto = (c.get("serve") or {}).get("rto_s")
print(f"cell OK: {segs} segments shipped, {replayed} sub-pushes "
      f"replayed on the standby, {len(refused)} fenced pushes refused, "
      f"RPO {lost} sub-pushes, RTO {rto}s, digest parity")
PY
        ;;
    *trainer_crash_mid_loop*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
lp = doc["loop"]
trained = lp.get("final_cursor_events", 0)
assert trained >= 1, (
    f"{sys.argv[1]}: ZERO feedback events trained — the continuous "
    "trainer never consumed the spool, the pass is vacuous")
assert lp.get("replayed_window", 0) >= 1, (
    f"{sys.argv[1]}: the resumed trainer replayed an EMPTY window — the "
    "kill landed on a checkpoint boundary and the exactly-once resume "
    "path was never exercised")
print(f"loop OK: {trained} events trained exactly-once, "
      f"{lp['replayed_window']} replayed after the kill, digests match")
PY
        ;;
    *retrieval_replica_death_mid_index_update*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
lp = doc["loop"]
incr = lp.get("incremental_updates", 0)
assert incr >= 1, (
    f"{sys.argv[1]}: ZERO incremental index updates committed under "
    "live traffic — the builder never moved the index mid-run, the "
    "pass is vacuous")
during = lp.get("retrievals_during_update", 0)
assert during >= 1, (
    f"{sys.argv[1]}: ZERO retrievals served during the update window — "
    "the frontend was never queried while the index was moving, the "
    "pass is vacuous")
assert lp.get("restarts", 0) >= 1 and lp.get("restored_version", 0) >= 1, (
    f"{sys.argv[1]}: the builder was never killed + resumed from a "
    "committed snapshot (restarts="
    f"{lp.get('restarts')}, restored_version={lp.get('restored_version')})")
assert lp.get("digests_match"), (
    f"{sys.argv[1]}: served candidates diverged from the brute-force "
    f"bypass witness ({lp.get('digest_served')} != "
    f"{lp.get('digest_witness')})")
print(f"retrieval OK: {incr} incremental updates, {during} retrievals "
      f"mid-update, builder resumed from v{lp['restored_version']}, "
      "served == bypass witness")
PY
        ;;
    *rollout_half_update*)
        python - "$verdict" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
lp = doc["loop"]
swaps = lp.get("swaps", [])
assert len(swaps) >= 2, (
    f"{sys.argv[1]}: {len(swaps)} version swap(s) observed — the serving "
    "replica never hot-swapped under load, the pass is vacuous")
assert lp.get("torn_version", 0) and not lp.get("torn_served", True), (
    f"{sys.argv[1]}: torn publication missing or SERVED")
assert lp.get("feedback", {}).get("serve_events", 0) >= 1, (
    f"{sys.argv[1]}: zero feedback events spooled — the emit hook never "
    "fired under load")
print(f"rollout OK: {len(swaps)} swaps, torn v{lp['torn_version']} and "
      f"corrupt v{lp['corrupt_version']} never served, "
      f"{lp['feedback']['serve_events']} feedback events spooled")
PY
        ;;
    esac
    wd=$(python -c 'import json,sys; print(json.load(open(sys.argv[1]))["workdir"])' "$verdict")
    tracedir="$wd"
    case "$verdict" in
    *cell_failover*) tracedir="$wd/primary" ;;  # pods trace in the CELL dir
    esac
    python scripts/trace_export.py --workdir "$tracedir" --out "$wd/trace.json"
    python - "$wd/trace.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
events = [e for e in doc.get("traceEvents", []) if e.get("ph") != "M"]
assert events, f"{sys.argv[1]}: merged trace is EMPTY"
spans = [e for e in events if e.get("ph") == "X"]
assert spans, f"{sys.argv[1]}: merged trace has no spans"
print(f"trace OK: {len(events)} events, {len(spans)} spans")
PY
    rm -rf "$wd"   # kept only for the export; drop after the check
done

# Aggregate the measured per-drill TTDs into the committed detection
# report — itself a gate: a drill whose expectation declares detection
# but whose verdict carries no check, or a control that paged, makes the
# aggregator exit non-zero.
env JAX_PLATFORMS=cpu python scripts/slo_report.py --detect $VERDICTS \
    --out DETECT.json

# The tier-1 SLO pulse, run here too so a catalog rot fails the smoke
# even when the drills themselves pass.
env JAX_PLATFORMS=cpu python scripts/slo_report.py --smoke

# Offline policy replay gate: every committed simulator fixture (recorded
# chaos timelines AND the mesh-shape autoscale surface — fixtures with a
# meta.shape_profile replay through the real MeshShapePolicy with the
# mesh_shape_converged invariant) plus the synthetic catalog (incl. the
# mis-tuned negative controls: hair-trigger straggler, too-short preempt
# grace, pinned-pathological mesh shape, alert budget squeezed under the
# healthy shed baseline) must pass its policy invariants,
# and each fixture replay must be byte-identical across back-to-back runs
# — the simulator's determinism contract, checked where the drills that
# feed it live.
SIMDIR=$(mktemp -d)
trap 'rm -f "$LOG"; rm -rf "$SIMDIR"' EXIT

env JAX_PLATFORMS=cpu python scripts/policy_replay.py --out-dir "$SIMDIR"

for fixture in tests/fixtures/sim/*.json; do
    name=$(basename "$fixture" .json)
    env JAX_PLATFORMS=cpu python scripts/policy_replay.py \
        --fixture "$fixture" --out "$SIMDIR/replay-$name-1.json"
    env JAX_PLATFORMS=cpu python scripts/policy_replay.py \
        --fixture "$fixture" --out "$SIMDIR/replay-$name-2.json"
    cmp "$SIMDIR/replay-$name-1.json" "$SIMDIR/replay-$name-2.json" || {
        echo "chaos_smoke: NONDETERMINISTIC replay for $fixture" >&2
        exit 1
    }
    echo "policy replay OK: $name (deterministic, invariants hold)"
done
