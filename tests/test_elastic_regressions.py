"""Regression tests for review findings: drain escalation, checkpoint
double-save/aborted-save handling, master-restart agent adoption."""

import itertools
import os

import optax

from easydl_tpu.core import MeshSpec, Trainer, TrainConfig, build_mesh
from easydl_tpu.core.checkpoint import CheckpointManager
from easydl_tpu.elastic.master import Master
from easydl_tpu.elastic.membership import Rendezvous
from easydl_tpu.models import get_model
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.utils.rpc import RpcClient
from easydl_tpu.elastic.master import MASTER_SERVICE

ports = itertools.count(9500)


def test_member_death_mid_planned_drain_escalates_to_kill():
    rdv = Rendezvous(desired_workers=2, port_alloc=lambda: next(ports))
    for a in ("a0", "a1"):
        rdv.register(a, "h", 2)
    for a in ("a0", "a1"):
        d = rdv.directive_for(a)
        if d.kind == "run":
            rdv.heartbeat(a, d.generation, "running")
    gen = rdv.generation
    # planned drain begins (scale 2 -> 1)
    rdv.set_desired_workers(1)
    assert rdv.directive_for("a0").kind == "quiesce"
    # a1 dies before reaching its quiesce boundary
    rdv.agents["a1"].last_heartbeat -= 100.0
    rdv.tick()
    # survivors must be escalated to KILL, not left waiting on the dead peer
    assert rdv.directive_for("a0").kind == "kill"
    rdv.heartbeat("a0", gen, "idle")
    assert rdv.generation == gen + 1 and rdv.members == ["a0"]


def test_checkpoint_double_save_is_noop(tmp_path, eight_devices):
    bundle = get_model("mlp", input_shape=(8, 8, 1), features=(32, 32))
    t = Trainer(bundle.init_fn, bundle.loss_fn, optax.adam(1e-2),
                TrainConfig(global_batch=32), mesh=build_mesh(MeshSpec(dp=8)))
    s = t.init_state()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    mgr.save(7, s)
    mgr.save(7, s)  # must not raise ENOTEMPTY / duplicate
    assert mgr.steps() == [7]


def test_checkpoint_aborted_save_is_cleared(tmp_path, eight_devices):
    bundle = get_model("mlp", input_shape=(8, 8, 1), features=(32, 32))
    t = Trainer(bundle.init_fn, bundle.loss_fn, optax.adam(1e-2),
                TrainConfig(global_batch=32), mesh=build_mesh(MeshSpec(dp=8)))
    s = t.init_state()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    # Simulate a crash mid-save: step dir with junk, no COMMITTED marker.
    debris = tmp_path / "step_00000003" / "leaf_00000"
    os.makedirs(debris)
    (debris / "0-999.npy").write_bytes(b"garbage")
    mgr.save(3, s)  # must clear debris and commit cleanly
    assert mgr.steps() == [3]
    abstract, _, _ = t._abstract_state()
    restored = mgr.restore(3, abstract, t.state_shardings())
    assert restored is not None


def test_master_adopts_unknown_heartbeat(tmp_path):
    master = Master(job_name="adopt", workdir=str(tmp_path), desired_workers=1).start()
    try:
        client = RpcClient(MASTER_SERVICE, master.address)
        client.wait_ready()
        # Heartbeat from an agent the (restarted) master has never seen.
        d = client.Heartbeat(pb.HeartbeatRequest(
            agent_id="ghost", generation=5, state="running", host="h9", slots=4,
        ))
        assert "ghost" in master.rendezvous.agents
        # The adopted agent is re-formed into a fresh generation.
        assert master.rendezvous.members == ["ghost"]
        client.close()
    finally:
        master.stop()
