"""Flash-attention kernel numerics vs the XLA reference path — forward and
backward (custom VJP), causal and bidirectional, multiple block splits, and
use inside a jitted transformer step. Kernels run in Pallas interpreter mode
on CPU (same code path the TPU compiles).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from easydl_tpu.ops.attention import _reference_attention
from easydl_tpu.ops.flash_attention import flash_attention


def rand_qkv(key, b=2, s=128, h=4, d=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, h, d), dtype)
    v = jax.random.normal(kv, (b, s, h, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [32, 64, 128])
def test_forward_matches_reference(causal, block):
    q, k, v = rand_qkv(jax.random.PRNGKey(0))
    scale = q.shape[-1] ** -0.5
    ref = _reference_attention(q, k, v, causal=causal, scale=scale)
    out = flash_attention(
        q, k, v, causal=causal, block_q=block, block_k=block, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = rand_qkv(jax.random.PRNGKey(1), b=1, s=64, h=2, d=16)
    scale = q.shape[-1] ** -0.5

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
        )
        return (out * jnp.cos(out)).sum()

    def loss_ref(q, k, v):
        out = _reference_attention(q, k, v, causal=causal, scale=scale)
        return (out * jnp.cos(out)).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_uneven_blocks_and_rectangular():
    # seq not equal to block multiples exercises the min() clamping
    q, k, v = rand_qkv(jax.random.PRNGKey(2), s=96, d=64)
    ref = _reference_attention(q, k, v, causal=True, scale=64**-0.5)
    out = flash_attention(q, k, v, causal=True, block_q=96, block_k=96, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_causal_cross_length_bottom_right_aligned():
    """s_q != s_k causal masking must match the reference path's
    bottom-right alignment (tril k=s_k-s_q) — e.g. decode: q_len 32 against a
    64-long KV cache attends all past keys, not just the first 32."""
    key = jax.random.PRNGKey(4)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, d, s_q, s_k = 2, 2, 32, 32, 64
    q = jax.random.normal(kq, (b, s_q, h, d))
    k = jax.random.normal(kk, (b, s_k, h, d))
    v = jax.random.normal(kv, (b, s_k, h, d))
    scale = d**-0.5
    ref = _reference_attention(q, k, v, causal=True, scale=scale)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
        return (o * jnp.cos(o)).sum()

    def loss_ref(q, k, v):
        o = _reference_attention(q, k, v, causal=True, scale=scale)
        return (o * jnp.cos(o)).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_causal_cross_length_sq_gt_sk_dead_rows():
    """s_q > s_k bottom-right-aligned causal: the first s_q - s_k query rows
    attend nothing. Both paths must define such rows as zero output with
    zero gradient (not softmax's uniform mean of V) — and agree on the live
    rows. Exercises dead rows both inside a mixed q-block (block 16 > 8
    dead rows? no: 32 dead rows span blocks) and whole-dead q-blocks."""
    key = jax.random.PRNGKey(6)
    kq, kk, kv = jax.random.split(key, 3)
    b, h, d, s_q, s_k = 2, 2, 16, 64, 32
    q = jax.random.normal(kq, (b, s_q, h, d))
    k = jax.random.normal(kk, (b, s_k, h, d))
    v = jax.random.normal(kv, (b, s_k, h, d))
    scale = d**-0.5
    n_dead = s_q - s_k
    ref = _reference_attention(q, k, v, causal=True, scale=scale)
    # block 16 divides both: dead rows cover 2 whole q-blocks; also run with
    # block 32 so one q-block mixes dead and live rows.
    for bq in (16, 32):
        out = flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=16, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out[:, :n_dead]), 0.0, err_msg=f"bq={bq} dead rows"
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5,
            err_msg=f"bq={bq}",
        )

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=16,
                            interpret=True)
        return (o * jnp.cos(o)).sum()

    def loss_ref(q, k, v):
        o = _reference_attention(q, k, v, causal=True, scale=scale)
        return (o * jnp.cos(o)).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_flash[0][:, :n_dead]), 0.0,
                               err_msg="dead rows must not leak dq")
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_untileable_length_falls_back_to_reference():
    """Lengths with no usable block divisor (e.g. 72 with block 48 → none
    ≥128-aligned) must not assert — the wrapper falls back to the XLA path."""
    q, k, v = rand_qkv(jax.random.PRNGKey(5), s=72, d=16)
    ref = _reference_attention(q, k, v, causal=True, scale=16**-0.5)
    out = flash_attention(q, k, v, causal=True, block_q=48, block_k=48, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    q, k, v = rand_qkv(jax.random.PRNGKey(3), dtype=jnp.bfloat16, s=64)
    ref = _reference_attention(q, k, v, causal=True, scale=q.shape[-1] ** -0.5)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2, rtol=2e-2
    )


def test_inside_jitted_train_step():
    """Flash path composes with jit + grad in a real model step."""
    import optax

    from easydl_tpu.core.mesh import MeshSpec
    from easydl_tpu.core.train_loop import TrainConfig, Trainer
    from easydl_tpu.models.registry import get_model

    bundle = get_model("gpt", size="test", seq_len=64, vocab=256, attention_impl="flash")
    trainer = Trainer(
        init_fn=bundle.init_fn,
        loss_fn=bundle.loss_fn,
        optimizer=optax.adam(1e-3),
        config=TrainConfig(global_batch=8),
        mesh_spec=MeshSpec(dp=2),
    )
    state = trainer.init_state()
    batch = next(iter(bundle.make_data(8)))
    state, metrics = trainer.train_step(state, batch)
    assert np.isfinite(jax.device_get(metrics)["loss"])

    # And matches the reference-attention model numerically.
    bundle_ref = get_model(
        "gpt", size="test", seq_len=64, vocab=256, attention_impl="reference"
    )
    trainer_ref = Trainer(
        init_fn=bundle_ref.init_fn,
        loss_fn=bundle_ref.loss_fn,
        optimizer=optax.adam(1e-3),
        config=TrainConfig(global_batch=8),
        mesh_spec=MeshSpec(dp=2),
    )
    state_ref = trainer_ref.init_state()
    batch_ref = next(iter(bundle_ref.make_data(8)))
    _, metrics_ref = trainer_ref.train_step(state_ref, batch_ref)
    np.testing.assert_allclose(
        jax.device_get(metrics)["loss"],
        jax.device_get(metrics_ref)["loss"],
        rtol=1e-3,
    )
