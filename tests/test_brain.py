"""Brain tests: startup-plan heuristics, the damped autoscaler (north-star
8→16→32 climb, oscillation resistance, bad-size memory), and the gRPC service
round trip — including a live master polling a live Brain.

The reference specifies only Brain's two query types
(docs/design/elastic-training-operator.md:106-112); the decision policy is
this framework's own (SURVEY.md §7 hard part 5).
"""

import time

import pytest

from easydl_tpu.api import ResourcePlan, RolePlan
from easydl_tpu.brain.convert import plan_from_proto, plan_to_proto
from easydl_tpu.brain.policy import Autoscaler, AutoscalerConfig, startup_plan
from easydl_tpu.brain.service import BRAIN_SERVICE, Brain
from easydl_tpu.proto import easydl_pb2 as pb
from easydl_tpu.utils.rpc import RpcClient


def features(family="mlp", **kw):
    f = pb.JobFeatures(job_name="j", model_family=family)
    for k, v in kw.items():
        setattr(f, k, v)
    return f


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def metrics(world, sps, step=1):
    return pb.StepMetrics(
        job_name="j", step=step, world_size=world, samples_per_sec=sps,
        step_time_s=0.1,
    )


# ---------------------------------------------------------------- startup plan

def test_startup_plan_quickstart_mlp_matches_reference_shape():
    # BASELINE config 1: "MNIST MLP, 1 PS + 2 CPU workers"
    plan = startup_plan(features("mlp", uses_ps=True))
    assert plan.replicas("worker") == 2
    assert plan.replicas("parameter_server") == 1
    assert plan.roles["worker"].resource.tpu is None  # CPU workers


def test_startup_plan_resnet_ddp():
    plan = startup_plan(features("resnet"))
    assert plan.replicas("worker") == 8
    assert plan.roles["worker"].resource.tpu.chips == 1
    assert plan.replicas("parameter_server") == 0


def test_startup_plan_scales_with_param_count():
    plan = startup_plan(features("gpt", model_params=1_500_000_000))
    assert plan.replicas("worker") >= 16


def test_startup_plan_deepfm_has_ps():
    plan = startup_plan(features("deepfm", uses_ps=True))
    assert plan.replicas("parameter_server") >= 1
    assert plan.replicas("worker") >= 1


def test_startup_plan_evaluator():
    plan = startup_plan(features("bert", uses_evaluator=True))
    assert plan.replicas("evaluator") == 1


# ---------------------------------------------------------------- autoscaler

def feed(a, world, sps, n=6, step0=0):
    for i in range(n):
        a.observe(metrics(world, sps, step=step0 + i))


def test_autoscaler_north_star_climb_8_to_32():
    clock = FakeClock()
    a = Autoscaler(AutoscalerConfig(max_workers=32, cooldown_s=10), clock)
    feed(a, 8, 800.0)  # 100 samples/sec/chip
    clock.advance(60)
    assert a.decide(8) == 16  # no smaller baseline -> assumed efficient

    feed(a, 16, 1550.0)  # ~97% efficiency vs 8-chip per-chip rate
    clock.advance(60)
    assert a.decide(16) == 32

    feed(a, 32, 3000.0)  # ~94% marginal efficiency: keep it
    clock.advance(60)
    assert a.decide(32) == 32


def test_autoscaler_reverts_inefficient_scaleup_and_remembers():
    clock = FakeClock()
    a = Autoscaler(AutoscalerConfig(max_workers=32, cooldown_s=10), clock)
    feed(a, 8, 800.0)
    clock.advance(60)
    assert a.decide(8) == 16

    # 16 chips barely faster than 8: marginal efficiency ~0.53 < 0.60 floor.
    feed(a, 16, 850.0)
    clock.advance(60)
    assert a.decide(16) == 8  # reverted

    # Even with renewed good numbers at 8, it won't retry the bad size.
    feed(a, 8, 800.0, n=10)
    clock.advance(60)
    assert a.decide(8) == 8
    assert 16 in a.status()["bad_sizes"]


def test_autoscaler_cooldown_prevents_oscillation():
    clock = FakeClock()
    a = Autoscaler(AutoscalerConfig(cooldown_s=30), clock)
    feed(a, 8, 800.0)
    clock.advance(60)
    assert a.decide(8) == 16
    feed(a, 16, 1550.0)
    clock.advance(5)  # within cooldown
    assert a.decide(16) == 16  # held despite good numbers


def test_autoscaler_scales_down_on_throughput_collapse():
    clock = FakeClock()
    a = Autoscaler(AutoscalerConfig(cooldown_s=1), clock)
    feed(a, 8, 800.0)
    clock.advance(10)
    # Collapse: per-chip rate drops to 20% of best.
    feed(a, 8, 160.0, n=20)
    clock.advance(10)
    assert a.decide(8) == 4


def test_autoscaler_needs_min_samples():
    clock = FakeClock()
    a = Autoscaler(AutoscalerConfig(min_samples=5), clock)
    feed(a, 8, 800.0, n=3)
    clock.advance(100)
    assert a.decide(8) == 8  # not enough evidence


# ---------------------------------------------------------------- conversion

def test_plan_proto_roundtrip():
    plan = startup_plan(features("deepfm", uses_ps=True, uses_evaluator=True))
    plan2 = plan_from_proto(plan_to_proto(plan))
    assert plan2.to_crd() == plan.to_crd()
    assert plan2.version == plan.version


# ---------------------------------------------------------------- service

def test_brain_grpc_roundtrip():
    brain = Brain().start()
    try:
        client = RpcClient(BRAIN_SERVICE, brain.address)
        resp = client.GetStartupPlan(features("resnet"))
        assert resp.has_plan and resp.plan.roles["worker"].replicas == 8

        # No newer plan yet.
        resp2 = client.GetPlan(pb.PlanRequest(job_name="j", current_version=resp.plan.version))
        assert not resp2.has_plan

        ack = client.ReportMetrics(metrics(8, 800.0))
        assert ack.ok
        client.close()
    finally:
        brain.stop()


def test_brain_replans_from_metrics():
    clock = FakeClock()
    brain = Brain(AutoscalerConfig(cooldown_s=0.0, min_samples=3), clock=clock)
    brain.set_plan(ResourcePlan(job_name="j", version=1,
                                roles={"worker": RolePlan(replicas=8)}))
    for i in range(5):
        clock.advance(5)
        brain.observe(metrics(8, 800.0, step=i))
    plan = brain.current_plan("j", newer_than=1)
    assert plan is not None and plan.replicas("worker") == 16
    assert plan.version == 2


def test_master_polls_brain_and_applies_plan(tmp_path):
    """Full loop: master polls a live Brain over gRPC and applies the replan
    to its rendezvous (docs/design/elastic-training-operator.md:110-114)."""
    from easydl_tpu.elastic.master import Master

    clock = FakeClock()
    brain = Brain(AutoscalerConfig(cooldown_s=0.0, min_samples=3), clock=clock).start()
    master = None
    try:
        brain.set_plan(ResourcePlan(job_name="poll-job", version=1,
                                    roles={"worker": RolePlan(replicas=2)}))
        master = Master(
            job_name="poll-job",
            workdir=str(tmp_path / "poll-master"),
            desired_workers=1,
            brain_address=brain.address,
            brain_poll_interval=0.1,
        ).start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if master.rendezvous.desired_workers == 2:
                break
            time.sleep(0.05)
        assert master.rendezvous.desired_workers == 2
        assert master.plan_version == 1

        # Metrics arrive at Brain -> replan -> master picks it up on next poll.
        for i in range(5):
            clock.advance(5)
            brain.observe(pb.StepMetrics(job_name="poll-job", step=i,
                                         world_size=2, samples_per_sec=100.0,
                                         step_time_s=0.1))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if master.rendezvous.desired_workers == 4:
                break
            time.sleep(0.05)
        assert master.rendezvous.desired_workers == 4
    finally:
        if master:
            master.stop()
        brain.stop()


# ---------------------------------------------------------------- durability

def test_brain_restart_resumes_versions_and_autoscale(tmp_path):
    """Kill the Brain mid-autoscale; the replacement must keep climbing to
    the 8→32 target with monotonically advancing plan versions. Without the
    persisted state the replacement's versions restart below the master's
    and every replan is rejected as stale (VERDICT r2 missing item 3)."""
    sd = str(tmp_path / "brain-state")
    clock = FakeClock()
    cfg = AutoscalerConfig(cooldown_s=10, min_samples=3, max_workers=32)

    brain = Brain(cfg, clock=clock, state_dir=sd)
    brain.set_plan(ResourcePlan(job_name="j", version=1,
                                roles={"worker": RolePlan(replicas=8)}))
    # climb 8 -> 16
    for i in range(4):
        clock.advance(5)
        brain.observe(metrics(8, 800.0, step=i))
    p16 = brain.current_plan("j", newer_than=1)
    assert p16 is not None and p16.replicas("worker") == 16
    assert p16.version == 2
    del brain  # killed mid-climb (no clean shutdown needed: state is synced)

    # replacement Brain: must resume, not reset
    brain2 = Brain(cfg, clock=clock, state_dir=sd)
    resumed = brain2.current_plan("j", newer_than=0)
    assert resumed is not None
    assert resumed.version == 2 and resumed.replicas("worker") == 16

    # keep climbing 16 -> 32 with healthy scaling efficiency
    clock.advance(60)
    for i in range(4):
        clock.advance(5)
        brain2.observe(metrics(16, 1550.0, step=10 + i))
    p32 = brain2.current_plan("j", newer_than=2)
    assert p32 is not None and p32.replicas("worker") == 32
    assert p32.version == 3  # strictly past the persisted max


def test_brain_restart_remembers_bad_sizes_and_windows(tmp_path):
    """The autoscaler's memory (bad sizes, per-size windows) survives too —
    a replacement must not retry a size the old Brain proved inefficient."""
    sd = str(tmp_path / "brain-state")
    clock = FakeClock()
    cfg = AutoscalerConfig(cooldown_s=10, min_samples=3, max_workers=32)
    a = Autoscaler(cfg, clock=clock)
    for i in range(4):
        a.observe(metrics(8, 800.0, step=i))
    clock.advance(60)
    assert a.decide(8) == 16
    for i in range(4):
        a.observe(metrics(16, 900.0, step=i))  # terrible marginal efficiency
    clock.advance(60)
    assert a.decide(16) == 8  # reverted, 16 remembered bad

    state = a.to_state()
    b = Autoscaler(cfg, clock=clock)
    b.restore_state(state)
    assert 16 in b._bad_sizes
    clock.advance(60)
    for i in range(4):
        b.observe(metrics(8, 800.0, step=10 + i))
    assert b.decide(8) == 8  # refuses the remembered-bad 16

    # cooldown survives as elapsed time: a decision 1s ago still gates
    c = Autoscaler(cfg, clock=clock)
    for i in range(4):
        c.observe(metrics(8, 800.0, step=i))
    clock.advance(60)
    assert c.decide(8) == 16  # starts the cooldown window
    snap = c.to_state()
    clock.advance(1)
    d = Autoscaler(cfg, clock=clock)
    d.restore_state(snap)
    for i in range(4):
        d.observe(metrics(8, 800.0, step=20 + i))
    assert d.decide(8) == 8  # still cooling down (1s < 10s)
    clock.advance(60)
    assert d.decide(8) == 16  # cooldown elapsed


def test_master_brain_both_restart_mid_climb(tmp_path):
    """The end-to-end regression VERDICT describes: master persisted at plan
    v2; Brain restarts; the job must still reach the scale target instead of
    deadlocking at the master's stale-version gate."""
    from easydl_tpu.elastic.master import Master

    sd = str(tmp_path / "brain-state")
    clock = FakeClock()
    cfg = AutoscalerConfig(cooldown_s=0.0, min_samples=3)
    brain = Brain(cfg, clock=clock, state_dir=sd).start()
    master = None
    try:
        brain.set_plan(ResourcePlan(job_name="bj", version=1,
                                    roles={"worker": RolePlan(replicas=2)}))
        master = Master(job_name="bj", workdir=str(tmp_path / "m"),
                        desired_workers=1, brain_address=brain.address,
                        brain_poll_interval=0.1).start()
        for i in range(5):
            clock.advance(5)
            brain.observe(pb.StepMetrics(job_name="bj", step=i, world_size=2,
                                         samples_per_sec=100.0, step_time_s=0.1))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if master.rendezvous.desired_workers == 4:
                break
            time.sleep(0.05)
        assert master.rendezvous.desired_workers == 4
        assert master.plan_version == 2
        brain.stop()

        # Brain pod replaced; master (plan_version=2) keeps polling.
        brain2 = Brain(cfg, clock=clock, state_dir=sd).start()
        try:
            master.brain_address = brain2.address
            for i in range(5):
                clock.advance(5)
                brain2.observe(pb.StepMetrics(job_name="bj", step=10 + i,
                                              world_size=4,
                                              samples_per_sec=195.0,
                                              step_time_s=0.1))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if master.rendezvous.desired_workers == 8:
                    break
                time.sleep(0.05)
            assert master.rendezvous.desired_workers == 8
            assert master.plan_version == 3
        finally:
            brain2.stop()
    finally:
        if master:
            master.stop()


def test_metrics_aggregation_survives_silent_rank0(tmp_path):
    """Brain input is the median over live members, not members[0]'s stream:
    a hung first member must not blind the autoscaler (VERDICT r2 weak 5)."""
    from easydl_tpu.elastic.master import Master

    master = Master(job_name="agg", workdir=str(tmp_path / "agg"),
                    desired_workers=3)
    master.brain_address = "unused:1"  # enable the aggregation path
    master.rendezvous.members = ["a0", "a1", "a2"]
    # a0 reported once long ago (hung since); a1/a2 report steadily
    master._record_metrics("a0", pb.StepMetrics(
        job_name="agg", step=1, world_size=3, samples_per_sec=50.0,
        step_time_s=0.5))
    for i in range(2, 6):
        master._record_metrics("a1", pb.StepMetrics(
            job_name="agg", step=i, world_size=3, samples_per_sec=300.0,
            step_time_s=0.1))
        master._record_metrics("a2", pb.StepMetrics(
            job_name="agg", step=i, world_size=3, samples_per_sec=302.0,
            step_time_s=0.1))
    agg = master._aggregate_metrics()
    assert agg is not None
    assert agg.step == 5
    assert 290 <= agg.samples_per_sec <= 310  # median, not a0's stale 50
    # a departed member's stale report is excluded entirely
    master.rendezvous.members = ["a1", "a2"]
    agg = master._aggregate_metrics()
    assert agg.samples_per_sec >= 300.0


def test_state_files_distinct_for_colliding_names(tmp_path):
    """Advisor r3 low: 'a/b' and 'a_b' sanitize identically — their state
    files must still be distinct or they overwrite each other."""
    sd = str(tmp_path / "bs")
    clock = FakeClock()
    brain = Brain(AutoscalerConfig(), clock=clock, state_dir=sd)
    brain.set_plan(ResourcePlan(job_name="a/b", version=5,
                                roles={"worker": RolePlan(replicas=4)}))
    brain.set_plan(ResourcePlan(job_name="a_b", version=9,
                                roles={"worker": RolePlan(replicas=2)}))
    import os
    assert len([f for f in os.listdir(sd) if f.endswith(".json")]) == 2
    brain2 = Brain(AutoscalerConfig(), clock=clock, state_dir=sd)
    assert brain2.current_plan("a/b", 0).version == 5
    assert brain2.current_plan("a_b", 0).version == 9


def test_persist_throttled_but_plan_changes_immediate(tmp_path):
    """Window-state persists are throttled (no fsync per StepMetrics); plan
    changes persist immediately; stop() flushes the throttled state."""
    import os
    sd = str(tmp_path / "bs")
    clock = FakeClock()
    cfg = AutoscalerConfig(cooldown_s=10, min_samples=3, max_workers=32)
    brain = Brain(cfg, clock=clock, state_dir=sd, persist_window_s=2.0)
    brain.set_plan(ResourcePlan(job_name="j", version=1,
                                roles={"worker": RolePlan(replicas=8)}))
    path = brain._job_path("j")
    writes = [os.path.getmtime(path)]

    def mtime_changed():
        m = os.path.getmtime(path)
        changed = m != writes[-1]
        if changed:
            writes.append(m)
        return changed

    # rapid-fire metrics within the window: no write per observation
    clock.advance(0.01)
    brain.observe(metrics(8, 800.0, step=0))
    clock.advance(0.01)
    brain.observe(metrics(8, 800.0, step=1))
    assert not mtime_changed()
    # enough samples + cooldown: a replan fires -> persisted IMMEDIATELY
    # even though the window has not elapsed
    clock.advance(10.5)
    brain.observe(metrics(8, 800.0, step=2))
    clock.advance(0.01)
    brain.observe(metrics(8, 800.0, step=3))
    assert mtime_changed()
    with open(path) as f:
        import json as _json
        assert _json.load(f)["plan"]["metadata"]["version"] == 2
    # dirty window state flushed on clean stop
    clock.advance(0.01)
    brain.observe(metrics(16, 1550.0, step=4))
    pre = os.path.getmtime(path)
    brain.stop()
    assert os.path.getmtime(path) != pre or not brain._jobs["j"].dirty


def test_legacy_state_file_migrated_not_shadowing(tmp_path):
    """A pre-digest-scheme brain-j.json must not overwrite the canonical
    digest file's fresher state on restart; it is migrated then removed."""
    import json as _json
    import os
    sd = str(tmp_path / "bs")
    os.makedirs(sd)
    clock = FakeClock()
    brain = Brain(AutoscalerConfig(), clock=clock, state_dir=sd)
    brain.set_plan(ResourcePlan(job_name="j", version=9,
                                roles={"worker": RolePlan(replicas=4)}))
    # simulate the legacy file left behind by the old filename scheme
    stale = {"job": "j",
             "plan": ResourcePlan(job_name="j", version=2,
                                  roles={"worker": RolePlan(replicas=8)}
                                  ).to_crd(),
             "autoscaler": {}}
    with open(os.path.join(sd, "brain-j.json"), "w") as f:
        _json.dump(stale, f)
    brain2 = Brain(AutoscalerConfig(), clock=clock, state_dir=sd)
    assert brain2.current_plan("j", 0).version == 9  # fresh state wins
    assert not os.path.exists(os.path.join(sd, "brain-j.json"))  # migrated
    # and a third restart still sees v9
    brain3 = Brain(AutoscalerConfig(), clock=clock, state_dir=sd)
    assert brain3.current_plan("j", 0).version == 9


# ---------------------------------------------------------- native core parity


def test_native_python_startup_parity_randomized():
    """The C++ startup-sizing core and its Python twin must agree on
    randomized feature vectors (SURVEY §2.1 item 2: Brain's native core)."""
    import random

    from easydl_tpu.brain.policy import (_py_startup_sizing, encode_features,
                                         startup_sizing_wire)
    from easydl_tpu.brain.policy import _native_call

    if _native_call("edb_startup", "F|mlp|0|0|0||0\n") is None:
        import pytest
        pytest.skip("no native toolchain")

    rng = random.Random(7)
    families = ["mlp", "resnet", "bert", "gpt", "deepfm", "widedeep",
                "unknown", "", "GPT", "Weird|Family\nName"]
    for trial in range(300):
        f = pb.JobFeatures(
            job_name="j",
            model_family=rng.choice(families),
            model_params=rng.choice(
                [0, 10_000, 250_000_000, 1_500_000_000, 6_000_000_000]),
            uses_ps=rng.random() < 0.5,
            uses_evaluator=rng.random() < 0.5,
        )
        f.accelerator.type = rng.choice(["", "v5e", "v4", "v5p"])
        f.accelerator.chips = rng.choice([0, 1, 4, 8])
        wire = encode_features(f)
        native = startup_sizing_wire(wire)
        python = _py_startup_sizing(wire)
        assert native == python, (
            f"trial {trial}: startup divergence\nwire: {wire!r}\n"
            f"native: {native!r}\npython: {python!r}"
        )


def test_native_python_decide_parity_randomized():
    """Two Autoscalers — one on the C++ core, one forced to the Python twin
    — fed identical randomized metric streams and clocks must make
    identical decisions at every step AND end with identical durable
    state."""
    import random

    from easydl_tpu.brain.policy import _native_call

    if _native_call("edb_decide", "T|0.0|0.0|1\n") is None:
        import pytest
        pytest.skip("no native toolchain")

    rng = random.Random(11)
    for trial in range(40):
        cfg = AutoscalerConfig(
            min_workers=rng.choice([1, 2]),
            max_workers=rng.choice([8, 16, 32]),
            min_samples=rng.choice([1, 2, 3]),
            cooldown_s=rng.choice([0.0, 5.0, 30.0]),
            scaleup_efficiency_floor=rng.choice([0.5, 0.8, 0.95]),
            marginal_efficiency_floor=rng.choice([0.3, 0.6, 0.9]),
            scaledown_throughput_ratio=rng.choice([0.2, 0.35, 0.6]),
            growth=rng.choice([2, 4]),
            window=rng.choice([4, 8, 20]),
        )
        clock_a, clock_b = FakeClock(), FakeClock()
        a = Autoscaler(cfg, clock=clock_a)               # native core
        b = Autoscaler(cfg, clock=clock_b, force_python=True)  # twin
        cur_a = cur_b = rng.choice([1, 2, 4, 8])
        for step in range(60):
            world = rng.choice([1, 2, 4, 8, 16, 32])
            sps = rng.uniform(0.1, 50.0) * world
            m = metrics(world, sps, step=step)
            a.observe(m)
            b.observe(m)
            dt = rng.choice([0.0, 1.0, 10.0, 60.0])
            clock_a.advance(dt)
            clock_b.advance(dt)
            if rng.random() < 0.5:
                ta = a.decide(cur_a)
                tb = b.decide(cur_b)
                assert ta == tb, (
                    f"trial {trial} step {step}: native {ta} != twin {tb}\n"
                    f"state:\n{a.encode_state(cur_a, clock_a.t)}"
                )
                cur_a, cur_b = ta, tb
        assert a.to_state() == b.to_state(), f"trial {trial}: durable drift"


# --------------------------------------------------------------------------
# restore_state hardening (ISSUE 8 satellite): a Brain pod crashed
# mid-journal-write leaves a torn/partial/garbage doc — the replacement
# must degrade to fresh state with a warning, never die on boot.
# --------------------------------------------------------------------------


@pytest.mark.parametrize("doc", [
    "not a dict at all",
    ["a", "list"],
    {"per_size": "garbage"},
    {"per_size": {"not_an_int": [1.0]}},
    {"per_size": {"2": "nan"}},
    {"per_size": {"2": [1.0, "bogus"]}},
    {"bad_sizes": ["x", None]},
    {"pending_check": 123},
    {"pending_check": ["a", "b"]},
    {"cooldown_elapsed_s": "soon"},
    {"best_per_chip": "fast"},
])
def test_restore_state_degrades_on_garbage_doc(doc):
    a = Autoscaler(AutoscalerConfig(), clock=lambda: 100.0,
                   force_python=True)
    a.restore_state(doc)  # must not raise
    # fresh-state semantics: no windows, no memory, no cooldown in force
    st = a.to_state()
    assert st["per_size"] == {}
    assert st["bad_sizes"] == []
    assert st["pending_check"] is None
    assert st["cooldown_elapsed_s"] is None
    # and the autoscaler still decides (holds steady with no samples)
    assert a.decide(4) == 4


def test_restore_state_filters_nonfinite_samples_but_keeps_the_rest():
    a = Autoscaler(AutoscalerConfig(), clock=lambda: 100.0,
                   force_python=True)
    a.restore_state({
        "per_size": {"2": [1.0, float("nan"), float("inf"), -3.0, 2.0]},
        "bad_sizes": [8],
        "best_per_chip": float("nan"),
        "cooldown_elapsed_s": 5.0,
    })
    st = a.to_state()
    assert st["per_size"] == {"2": [1.0, 2.0]}
    assert st["bad_sizes"] == [8]
    assert st["best_per_chip"] == 0.0  # NaN scrubbed
    assert st["cooldown_elapsed_s"] == 5.0


def test_restore_state_round_trip_still_exact_for_good_docs():
    clock = {"t": 0.0}
    a = Autoscaler(AutoscalerConfig(min_samples=3), clock=lambda: clock["t"],
                   force_python=True)
    for step in range(6):
        a.observe(pb.StepMetrics(step=step, samples_per_sec=100.0,
                                 world_size=2))
    a.decide(2)
    snap = a.to_state()
    b = Autoscaler(AutoscalerConfig(min_samples=3), clock=lambda: clock["t"],
                   force_python=True)
    b.restore_state(snap)
    assert b.to_state() == snap
