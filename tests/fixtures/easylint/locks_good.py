"""Known-good fixture: the blocking-call-under-lock rule MUST stay quiet —
work outside the hold, deferred closures, and non-lock contexts."""

import subprocess
import time


class Shard:
    def __init__(self, lock, pool):
        self._lock = lock
        self._pool = pool

    def sleep_outside(self):
        with self._lock:
            snapshot = dict()
        time.sleep(0.1)  # outside the hold: fine
        return snapshot

    def deferred_under_lock(self):
        with self._lock:
            def later():
                # defined under the lock but runs after release: fine
                time.sleep(0.1)
                subprocess.run(["true"])

            self._pool.submit(later)

    def non_lock_context(self, path):
        with open(path) as f:  # a file, not a lock: fine
            time.sleep(0.0)
            return f.read()
