#!/usr/bin/env python3
"""Measure the quiesce-consensus allgather cost (VERDICT r3 weak 4 / next 9).

The elastic worker reaches a step-boundary quiesce consensus via a tiny
``process_allgather`` (easydl_tpu/elastic/worker.py). This script records
what one such call costs at world N (default 4) on this host: it spawns N
single-device CPU jax processes joined by ``jax.distributed.initialize``
(the same transport a real multi-host job uses, minus the network), warms
up, then times many back-to-back allgathers of the worker's exact 2-float
payload.

Output (rank 0): one JSON line with per-call latency stats and the implied
per-step overhead fraction for representative step times at the legacy
every-step cadence vs the auto cadence (sync_target_s=1.0), which the
worker now uses by default.

Usage: python scripts/measure_consensus.py [--world 4] [--iters 300]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child(rank: int, world: int, coord: str, iters: int) -> None:
    import numpy as np

    import jax
    from jax.experimental import multihost_utils

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=world, process_id=rank)
    payload = np.asarray([0.0, 0.005], np.float64)  # the worker's payload
    for _ in range(20):  # warmup (first call compiles/establishes channels)
        multihost_utils.process_allgather(payload)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        multihost_utils.process_allgather(payload)
        times.append(time.perf_counter() - t0)
    if rank == 0:
        import numpy as np  # noqa: F811

        arr = np.asarray(times)
        med = float(np.median(arr))
        from easydl_tpu.elastic.worker import consensus_interval

        overhead = {}
        for step_ms in (5, 50, 3200):
            dt = step_ms / 1000.0
            every = med / (dt + med)  # legacy sync_every=1
            k = consensus_interval(1.0, dt)
            auto = (med / k) / (dt + med / k)
            overhead[f"step_{step_ms}ms"] = {
                "every_step_pct": round(100 * every, 3),
                "auto_interval_steps": k,
                "auto_pct": round(100 * auto, 4),
            }
        print(json.dumps({
            "world": world,
            "iters": iters,
            "allgather_median_us": round(med * 1e6, 1),
            "allgather_p95_us": round(float(np.percentile(arr, 95)) * 1e6, 1),
            "allgather_mean_us": round(float(arr.mean()) * 1e6, 1),
            "overhead": overhead,
        }))
    jax.distributed.shutdown()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--child", type=int, default=-1, help=argparse.SUPPRESS)
    ap.add_argument("--coord", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child >= 0:
        child(args.child, args.world, args.coord, args.iters)
        return

    sys.path.insert(0, REPO)
    from easydl_tpu.utils.env import run_cpu_rank_fleet

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run_cpu_rank_fleet(
        [[sys.executable, os.path.abspath(__file__),
          "--world", str(args.world), "--iters", str(args.iters),
          "--child", str(rank), "--coord", f"127.0.0.1:{port}"]
         for rank in range(args.world)],
        n_local_devices=1, timeout=600, cwd=REPO,
    )


if __name__ == "__main__":
    main()
