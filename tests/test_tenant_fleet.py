"""TenantFleet unit tests (no subprocesses): grant bootstrap, the
drain-then-kill preemption contract, escalation accounting, and the
evidence document's offline byte-replay."""

from easydl_tpu.brain.arbiter import ArbiterConfig, replay_decision_log
from easydl_tpu.controller.fleet import TenantFleet, TenantJob


class FakeAgent:
    def __init__(self, aid, master, drain_after_ticks=1):
        self.aid = aid
        self.master = master
        self.noticed = False
        self.stopped = False
        self._drain_after = drain_after_ticks
        self._worker = True

    @property
    def worker_pid(self):
        return 1234 if self._worker else None

    def notify_preemption(self):
        self.noticed = True

    def stop(self):
        assert not self._worker, \
            "fleet stopped an agent whose worker was still alive"
        self.stopped = True

    def tick(self):
        """Harness-driven drain progress: the worker exits some ticks
        after the notice (the quiesce walk)."""
        if self.noticed and self._worker:
            self._drain_after -= 1
            if self._drain_after <= 0:
                self._worker = False
                self.master.members = [
                    m for m in self.master.members if m != self.aid]


class FakeMaster:
    def __init__(self):
        self.members = []

    def status(self):
        return {"members": list(self.members)}


def build_fleet(total=3, holddown=0.0, drain_timeout=100.0):
    agents = {}

    def factory(aid, master, job):
        a = FakeAgent(aid, master)
        agents[aid] = a
        master.members = master.members or [aid]  # first agent = member
        return a

    fleet = TenantFleet(
        total, factory,
        ArbiterConfig(holddown_s=holddown, max_preemptions_per_decision=1),
        drain_timeout_s=drain_timeout, epoch=0.0)
    return fleet, agents


def test_bootstrap_grants_spawn_agents_immediately():
    fleet, agents = build_fleet(total=3)
    for name, pri, demand in (("hi", 2, 2), ("lo", 0, 2)):
        fleet.add_job(TenantJob(name=name, master=FakeMaster(), workdir=".",
                                priority=pri, min_chips=1, max_chips=2,
                                demand=demand))
    fleet.tick(now=0.0)
    assert fleet.allocations() == {"hi": 2, "lo": 1}
    assert len(agents) == 3


def test_preemption_drains_before_kill_and_regrants():
    fleet, agents = build_fleet(total=2)
    hi_m, lo_m = FakeMaster(), FakeMaster()
    fleet.add_job(TenantJob(name="hi", master=hi_m, workdir=".",
                            priority=2, min_chips=0, max_chips=2, demand=0))
    fleet.add_job(TenantJob(name="lo", master=lo_m, workdir=".",
                            priority=0, min_chips=0, max_chips=2, demand=2))
    fleet.tick(now=0.0)
    assert fleet.allocations() == {"hi": 0, "lo": 2}
    victim_pool = dict(agents)
    fleet.set_demand("hi", 2)
    d = fleet.tick(now=1.0)
    assert d["preemptions"]  # notice delivered, chip NOT yet moved
    assert fleet.allocations() == {"hi": 0, "lo": 2}
    victim = next(a for a in victim_pool.values() if a.noticed)
    # While the drain is pending the fleet must NOT decide again (the
    # mid-flight chip would read as free supply).
    assert fleet.tick(now=1.2) is None
    assert fleet.allocations() == {"hi": 0, "lo": 2}
    victim.tick()  # worker exits at its step boundary
    fleet.tick(now=1.5)
    assert victim.stopped  # stop() asserts the worker was already dead
    assert fleet.allocations() == {"hi": 1, "lo": 1}
    mark = fleet.preempt_drains[0]
    assert mark["job"] == "lo" and mark["to_job"] == "hi"
    assert mark["worker_alive_at_stop"] is False
    assert mark["escalated"] is False


def test_drain_escalation_is_recorded_never_silent():
    fleet, agents = build_fleet(total=2, drain_timeout=5.0)
    fleet.add_job(TenantJob(name="hi", master=FakeMaster(), workdir=".",
                            priority=2, min_chips=0, max_chips=2, demand=0))
    fleet.add_job(TenantJob(name="lo", master=FakeMaster(), workdir=".",
                            priority=0, min_chips=0, max_chips=2, demand=2))
    fleet.tick(now=0.0)
    fleet.set_demand("hi", 2)
    fleet.tick(now=1.0)
    victim = next(a for a in agents.values() if a.noticed)
    victim._worker = False  # wedge: worker dies but master never dropped it
    victim.master.members = [victim.aid]

    def never_drained():  # master still counts it a member -> not drained
        fleet.tick(now=3.0)
        return fleet._pending

    assert never_drained()
    fleet.tick(now=7.0)  # past the deadline: escalate, record, move on
    assert fleet.preempt_drains[0]["escalated"] is True
    assert fleet.allocations()["hi"] == 1


def test_evidence_decision_log_replays_byte_identical():
    fleet, _ = build_fleet(total=3)
    fleet.add_job(TenantJob(name="a", master=FakeMaster(), workdir=".",
                            priority=1, min_chips=1, max_chips=3, demand=3))
    fleet.add_job(TenantJob(name="b", master=FakeMaster(), workdir=".",
                            priority=0, min_chips=1, max_chips=3, demand=3))
    for t in (0.0, 1.0, 2.0):
        fleet.tick(now=t)
    ev = fleet.evidence()
    rep = replay_decision_log(ev["decision_log"])
    assert rep["identical"] and rep["decisions"] == 3
    assert ev["final_allocations"] == {"a": 2, "b": 1}
    # demand history rides the profile for the offline checks
    assert ev["profile"]["jobs"][0]["demand"] == [[0.0, 3]]


def test_two_preemptions_one_decision_take_two_different_victims():
    """Review finding (r20): with max_preemptions >= 2, one decision can
    take two chips from one donor — the fleet must drain two DIFFERENT
    agents, never queue the same victim twice (which recorded a drain
    that never happened and granted a phantom chip)."""
    agents = {}

    def factory(aid, master, job):
        a = FakeAgent(aid, master)
        agents[aid] = a
        master.members = master.members or [aid]
        return a

    fleet = TenantFleet(
        3, factory,
        ArbiterConfig(holddown_s=0.0, max_preemptions_per_decision=2),
        drain_timeout_s=100.0, epoch=0.0)
    fleet.add_job(TenantJob(name="hi", master=FakeMaster(), workdir=".",
                            priority=2, min_chips=0, max_chips=3, demand=0))
    fleet.add_job(TenantJob(name="lo", master=FakeMaster(), workdir=".",
                            priority=0, min_chips=1, max_chips=3, demand=3))
    fleet.tick(now=0.0)
    assert fleet.allocations() == {"hi": 0, "lo": 3}
    fleet.set_demand("hi", 2)
    d = fleet.tick(now=1.0)
    assert len(d["preemptions"]) == 2
    victims = {p.agent_id for p in fleet._pending}
    assert len(victims) == 2  # two DIFFERENT agents mid-drain
    for a in agents.values():
        if a.noticed:
            a.tick()
    fleet.tick(now=2.0)
    assert fleet.allocations() == {"hi": 2, "lo": 1}
    assert len(agents) == 5  # 3 bootstrap + 2 re-grants, no phantom
    assert len(fleet.preempt_drains) == 2
    assert {m["agent"] for m in fleet.preempt_drains} == victims
