"""Counted error swallows: ``easydl_swallowed_errors_total{site}``.

The framework's never-raise paths (metric emission, tracing, best-effort
cleanup) all share one idiom — a broad ``except Exception`` — and easylint's
``counted-swallow`` rule (analysis/rules/swallow.py) requires each of those
handlers to log, count, or re-raise. This module is the COUNT option made
one call: ``count_swallowed("obs.tracing.configure")`` increments a
per-site counter on the process registry, so a dead subsystem that fails a
thousand times an hour shows up as a climbing series on /metrics instead
of as silence. The ``site`` label is a short dotted code location, stable
across refactors (it names the seam, not the line number).

``count_swallowed`` itself MUST never raise — it is called from inside the
paths whose failures it records — so its last line is the one swallow in
the tree that cannot count itself; easylint exempts this module for
exactly that reason (swallow.EXEMPT_PATHS).
"""

from __future__ import annotations

from typing import Optional

_counter = None


def count_swallowed(site: str, error: Optional[BaseException] = None) -> None:
    """Record one swallowed error at ``site``. Never raises.

    ``error`` is accepted (and currently unused) so call sites can hand
    over the exception without a conditional — a future debug mode can
    sample it without touching every caller.
    """
    global _counter
    try:
        if _counter is None:
            from easydl_tpu.obs.registry import get_registry

            _counter = get_registry().counter(
                "easydl_swallowed_errors_total",
                "Errors swallowed on never-raise paths, by site. A "
                "climbing series means a subsystem is failing silently "
                "— triage the site before trusting its output.",
                ("site",),
            )
        _counter.inc(site=site)
    except Exception:
        pass


COUNTER_NAME = "easydl_swallowed_errors_total"
