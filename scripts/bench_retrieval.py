#!/usr/bin/env python
"""Retrieval-tier benchmark: recall@k, incremental freshness, and fleet
Retrieve latency — BENCH_RETRIEVAL.json, next to BENCH_SERVE.json.

Three cells, each against the acceptance criteria the retrieval tier
ships under:

* **recall@k** — a seeded Gaussian catalog is indexed by the real
  :class:`AnnIndex` (IVF-flat, Lloyd-refined centroids) and queried at
  the production ``EASYDL_RETRIEVAL_NPROBE`` default; recall is counted
  against exact brute force over the same rows. A full-probe pass must
  be EXACT (the index degenerates to brute force at nprobe >= nlist —
  the identity the chaos drill's digest witness stands on).
* **freshness** — the real :class:`IndexBuilder` tails a real push WAL
  (ps/wal.py frames, loop/spool.py cursors) while a
  :class:`ModelVersionWatcher` adopts each published snapshot; the cell
  measures push-ack -> candidate-retrievable-through-an-adopted-snapshot
  per item and reports p50/p99 against
  ``EASYDL_RETRIEVAL_FRESHNESS_SLO_S``.
* **fleet** — two real gRPC serving replicas behind the ServeRouter
  (session-affine routing, the same Retrieve proxy production uses),
  closed-loop drivers, end-to-end p50/p99 with retrieval in the path
  and zero errors.

``--smoke`` shrinks counts so the whole file runs in seconds inside
tier-1 (tests/test_retrieval.py); the full run writes the committed
BENCH_RETRIEVAL.json.

    python scripts/bench_retrieval.py --out BENCH_RETRIEVAL.json
    python scripts/bench_retrieval.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from easydl_tpu.loop import publish as model_publish  # noqa: E402
from easydl_tpu.ps import wal  # noqa: E402
from easydl_tpu.ps.client import LocalPsClient  # noqa: E402
from easydl_tpu.ps.read_client import PsReadClient  # noqa: E402
from easydl_tpu.ps.table import TableSpec  # noqa: E402
from easydl_tpu.retrieval.index import (  # noqa: E402
    AnnIndex,
    IndexBuilder,
    brute_force_topk,
)
from easydl_tpu.serve import ServeConfig, ServeFrontend  # noqa: E402
from easydl_tpu.serve.router import ServeRouter  # noqa: E402
from easydl_tpu.utils.env import knob_float, knob_int  # noqa: E402

USER_TABLE = "tt_user"
ITEM_TABLE = "tt_item"


def _pct(sorted_vals, p: float) -> float:
    if not len(sorted_vals):
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(p / 100.0 *
                                            (len(sorted_vals) - 1))))
    return float(sorted_vals[i])


# ------------------------------------------------------------- recall cell
def recall_cell(args, seed: int = 5) -> dict:
    rng = np.random.default_rng(seed)
    n, dim, k = args.items, args.dim, args.k
    nlist = knob_int("EASYDL_RETRIEVAL_NLIST")
    nprobe = knob_int("EASYDL_RETRIEVAL_NPROBE")
    ids = np.arange(1, n + 1, dtype=np.int64)
    vecs = rng.standard_normal((n, dim)).astype(np.float32)
    index = AnnIndex(dim, nlist=nlist, seed=seed, min_rebuild_rows=1)
    index.upsert(ids, vecs)
    index.maybe_rebuild()
    queries = rng.standard_normal((args.queries, dim)).astype(np.float32)
    want, _ = brute_force_topk(ids, vecs, queries, k)
    t0 = time.perf_counter()
    got, _ = index.search(queries, k, nprobe=nprobe)
    ann_s = time.perf_counter() - t0
    hit = sum(len(set(map(int, g)) & set(map(int, w)))
              for g, w in zip(got, want))
    recall = hit / float(want.size)
    exact, _ = index.search(queries, k, nprobe=nlist)
    full_probe_exact = bool(np.array_equal(exact, want))
    t0 = time.perf_counter()
    brute_force_topk(ids, vecs, queries, k)
    brute_s = time.perf_counter() - t0
    return {
        "items": n, "dim": dim, "k": k, "nlist": nlist, "nprobe": nprobe,
        "recall_at_k": round(recall, 4),
        "full_probe_exact": full_probe_exact,
        "ann_search_ms_total": round(ann_s * 1e3, 3),
        "brute_force_ms_total": round(brute_s * 1e3, 3),
        "queries": int(args.queries),
    }


# ---------------------------------------------------------- freshness cell
def freshness_cell(args, seed: int = 7) -> dict:
    """push-ack -> retrievable-through-an-adopted-snapshot, per item.

    The WAL write IS the push ack (a PS shard appends the record before
    ACKing), so the measured window covers exactly what production pays:
    spool tail -> row pull -> upsert -> snapshot publish -> watcher
    adoption."""
    rng = np.random.default_rng(seed)
    dim = args.dim
    rows: dict = {}

    def row_reader(ids: np.ndarray) -> np.ndarray:
        return np.stack([rows.get(int(i), np.zeros(dim, np.float32))
                         for i in np.asarray(ids).ravel()])

    samples = []
    with tempfile.TemporaryDirectory(prefix="bench-retrieval-") as wd:
        epoch_dir = os.path.join(wd, "ps-wal", "shard-0", "epoch-1")
        os.makedirs(epoch_dir)
        writer = wal.PsWal(epoch_dir, segment_bytes=1 << 20, sync_s=0.0)
        builder = IndexBuilder(
            wd, ITEM_TABLE, row_reader, dim,
            state_dir=os.path.join(wd, "state"),
            publish_dir=os.path.join(wd, "index"),
            nlist=knob_int("EASYDL_RETRIEVAL_NLIST"), ckpt_every=1)
        adopted: dict = {"index": None}
        watcher = model_publish.ModelVersionWatcher(
            os.path.join(wd, "index"),
            lambda m, a: AnnIndex.from_arrays(m, a),
            on_swap=lambda v, idx: adopted.__setitem__("index", idx),
            replica="bench", poll_s=0.005)
        # seed catalog first, then measure singles against the moving tail
        base = np.arange(1, args.fresh_base + 1, dtype=np.int64)
        base_vecs = rng.standard_normal(
            (len(base), dim)).astype(np.float32)
        for i, v in zip(base, base_vecs):
            rows[int(i)] = v
        writer.append(wal.encode_push_parts(
            ITEM_TABLE, base, base_vecs, 1.0))
        writer.sync()
        builder.poll_once()
        builder.snapshot_if_due(force=True)
        watcher.poll_once()
        for j in range(args.fresh_items):
            iid = int(args.fresh_base + 1 + j)
            vec = rng.standard_normal(dim).astype(np.float32)
            rows[iid] = vec
            t0 = time.perf_counter()
            writer.append(wal.encode_push_parts(
                ITEM_TABLE, np.asarray([iid], np.int64), vec[None, :],
                1.0))
            writer.sync()
            while True:
                builder.poll_once()
                builder.snapshot_if_due()  # ckpt_every=1: due per update
                watcher.poll_once()
                idx = adopted["index"]
                if idx is not None and iid in map(
                        int, idx.ids[:len(idx)]):
                    break
                time.sleep(0.001)
            samples.append(time.perf_counter() - t0)
        writer.close()
        watcher.stop()
    samples.sort()
    slo = knob_float("EASYDL_RETRIEVAL_FRESHNESS_SLO_S")
    return {
        "items_measured": len(samples),
        "base_catalog": int(args.fresh_base),
        "p50_s": round(_pct(samples, 50), 5),
        "p99_s": round(_pct(samples, 99), 5),
        "max_s": round(samples[-1], 5) if samples else 0.0,
        "slo_s": slo,
        "within_slo": bool(samples) and samples[-1] <= slo,
    }


# -------------------------------------------------------------- fleet cell
def fleet_cell(args, seed: int = 9) -> dict:
    """Two real gRPC replicas behind the ServeRouter, retrieval in the
    request path end-to-end: router Retrieve proxy -> replica ->
    PsReadClient user-tower pull -> ANN search."""
    from easydl_tpu.proto import easydl_pb2 as pb
    from easydl_tpu.serve.frontend import SERVE_SERVICE
    from easydl_tpu.utils.rpc import GRPC_MSG_OPTIONS, RpcClient

    rng = np.random.default_rng(seed)
    dim, fields, k = args.dim, 3, args.k
    client = LocalPsClient(num_shards=2, coalesce=False)
    client.create_table(TableSpec(name=USER_TABLE, dim=dim,
                                  optimizer="sgd", lr=1.0, init_std=0.0,
                                  seed=2))
    ctx_ids = np.arange(1, args.fleet_users * fields + 1, dtype=np.int64)
    client.push(USER_TABLE, ctx_ids,
                -rng.standard_normal(
                    (len(ctx_ids), dim)).astype(np.float32), scale=1.0)
    item_ids = np.arange(1, args.items + 1, dtype=np.int64)
    item_vecs = rng.standard_normal((args.items, dim)).astype(np.float32)
    index = AnnIndex(dim, nlist=knob_int("EASYDL_RETRIEVAL_NLIST"),
                     seed=seed, min_rebuild_rows=1)
    index.upsert(item_ids, item_vecs)
    index.maybe_rebuild()
    frontends, servers = [], []
    for i in range(2):
        fe = ServeFrontend(
            PsReadClient(client),
            ServeConfig(table=USER_TABLE, fields=fields, dense_dim=0,
                        max_wait_ms=1.0, request_timeout_s=30.0),
            name=f"bench-r{i}")
        fe.attach_retrieval(USER_TABLE)
        fe.set_index(1, index)
        frontends.append(fe)
        servers.append(fe.serve())
    router = ServeRouter(
        addresses={f"r{i}": s.address for i, s in enumerate(servers)},
        timeout_s=30.0)
    rserver = router.serve()
    lat: list = []
    errors = [0]
    mu = threading.Lock()
    user_ctx = ctx_ids.reshape(args.fleet_users, fields)

    def worker(wid: int) -> None:
        cl = RpcClient(SERVE_SERVICE, f"localhost:{rserver.port}",
                       timeout=30.0, options=GRPC_MSG_OPTIONS)
        wrng = np.random.default_rng(seed + wid)
        for i in range(args.fleet_requests_per_thread):
            u = int(wrng.integers(0, args.fleet_users))
            t0 = time.perf_counter()
            try:
                resp = cl.Retrieve(pb.RetrieveRequest(
                    raw_user_ids=user_ctx[u].astype("<i8").tobytes(),
                    user_fields=fields, k=k,
                    session_id=f"s{wid}-{i % 16}"))
                ok = bool(resp.ok)
            except Exception:
                ok = False
            dt = time.perf_counter() - t0
            with mu:
                lat.append(dt)
                if not ok:
                    errors[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(args.fleet_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    router.stop()
    for fe in frontends:
        fe.stop()
    lat.sort()
    return {
        "replicas": 2,
        "requests": len(lat),
        "errors": int(errors[0]),
        "qps": round(len(lat) / max(1e-9, wall), 1),
        "p50_ms": round(_pct(lat, 50) * 1e3, 3),
        "p99_ms": round(_pct(lat, 99) * 1e3, 3),
        "router_counters": {kk: vv for kk, vv in
                            router.counters.items() if vv},
    }


def main() -> int:
    ap = argparse.ArgumentParser(description="retrieval-tier benchmark")
    ap.add_argument("--out", default=os.path.join(
        REPO, "BENCH_RETRIEVAL.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized counts (tier-1 rides this)")
    ap.add_argument("--items", type=int, default=800)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--queries", type=int, default=128)
    ap.add_argument("--fresh-base", type=int, default=256)
    ap.add_argument("--fresh-items", type=int, default=40)
    ap.add_argument("--fleet-users", type=int, default=32)
    ap.add_argument("--fleet-threads", type=int, default=4)
    ap.add_argument("--fleet-requests-per-thread", type=int, default=120)
    args = ap.parse_args()
    if args.smoke:
        args.queries = 64
        args.fresh_items = 10
        args.fleet_threads = 2
        args.fleet_requests_per_thread = 40

    recall = recall_cell(args)
    fresh = freshness_cell(args)
    fleet = fleet_cell(args)
    doc = {
        "bench": "retrieval",
        "host": {"platform": platform.platform(),
                 "python": sys.version.split()[0],
                 "cpus": os.cpu_count()},
        "config": {"smoke": bool(args.smoke), "items": args.items,
                   "dim": args.dim, "k": args.k},
        "results": {"recall": recall, "freshness": fresh, "fleet": fleet},
        "acceptance": {
            # the ISSUE-17 floor: ANN at the production nprobe default
            # keeps >= 0.9 of the brute-force candidates
            "recall_floor": recall["recall_at_k"] >= 0.9,
            # nprobe >= nlist degenerates to EXACT brute force — the
            # identity the chaos drill's digest witness stands on
            "full_probe_exact": recall["full_probe_exact"],
            # every measured push lands inside the freshness SLO
            "freshness_slo": fresh["within_slo"],
            "fleet_zero_errors": fleet["errors"] == 0
                and fleet["requests"] > 0,
        },
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps(doc["results"], indent=2, sort_keys=True))
    gates = doc["acceptance"]
    print("acceptance:", json.dumps(gates, sort_keys=True))
    return 0 if all(gates.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
