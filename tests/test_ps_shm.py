"""Zero-copy shared-memory pull transport: mirror parity, the
co-location handshake, freshness, and every fallback edge.

The transport's contract (architecture.md §6): shm serves EXACTLY what
the wire would — bit-identical rows (mirrored ones from the segment,
absent ones via the shared deterministic lazy init), a push-version tag
never fresher than the wire's, and a silent return to gRPC on ANY
mismatch (remote host, revoked segment, numpy backend, capacity
overflow, consistency gates: cutover / fence / restore). Skipped
wholesale when the native toolchain is unavailable (the numpy fallback
has no mirror — and advertises none)."""

import numpy as np
import pytest

from easydl_tpu.ps import PsShard, ShardedPsClient, TableSpec
from easydl_tpu.ps import build as ps_build
from easydl_tpu.ps import shm as ps_shm
from easydl_tpu.ps.read_client import PsReadClient
from easydl_tpu.ps.table import EmbeddingTable
from easydl_tpu.serve import HotIdCache

pytestmark = pytest.mark.skipif(
    ps_build.load_native() is None,
    reason="native embedding store unavailable (no toolchain)")


def spec(**kw):
    base = dict(name="emb", dim=8, init_std=0.01, seed=7,
                optimizer="adagrad", lr=0.05)
    base.update(kw)
    return TableSpec(**base)


def seeded_table(n=200, dim=8, **kw):
    t = EmbeddingTable(spec(dim=dim, **kw), backend="native")
    rng = np.random.default_rng(1)
    ids = np.arange(n, dtype=np.int64)
    t.push(ids, rng.standard_normal((n, dim)).astype(np.float32))
    return t, ids


# ------------------------------------------------------------- table level
def test_export_gather_parity_and_version():
    t, ids = seeded_table()
    assert t.shm_export(8 << 20)
    name, nonce = t.shm_info()
    r = ps_shm.open_reader(name, nonce)
    assert r is not None
    rows, version = r.pull(ids)
    np.testing.assert_array_equal(rows, t.pull(ids))
    assert version == t.push_version
    r.close()


def test_wrong_nonce_and_missing_segment_refuse():
    t, _ids = seeded_table()
    assert t.shm_export(8 << 20)
    name, nonce = t.shm_info()
    assert ps_shm.open_reader(name, nonce + 2) is None
    assert ps_shm.open_reader("/eds-no-such-segment", 1) is None


def test_missing_ids_materialise_via_shared_lazy_init():
    """Ids never pushed are absent from the mirror; the reader computes
    the deterministic init locally — bit-identical to a server pull."""
    t, _ids = seeded_table()
    assert t.shm_export(8 << 20)
    r = ps_shm.open_reader(*t.shm_info())
    fresh = np.arange(50_000, 50_040, dtype=np.int64)
    rows, _v = r.pull(fresh)
    np.testing.assert_array_equal(rows, t.pull(fresh))
    r.close()


def test_push_write_through_and_version_monotone():
    t, ids = seeded_table()
    assert t.shm_export(8 << 20)
    r = ps_shm.open_reader(*t.shm_info())
    _rows, v0 = r.pull(ids[:16])
    rng = np.random.default_rng(2)
    t.push(ids[:16], rng.standard_normal((16, 8)).astype(np.float32))
    rows, v1 = r.pull(ids[:16])
    np.testing.assert_array_equal(rows, t.pull(ids[:16]))
    assert v1 > v0 and v1 == t.push_version
    # import rewrites rows too (restore/migration path)
    t.import_rows(ids[:4], np.ones((4, t.spec.row_width), np.float32))
    rows, v2 = r.pull(ids[:4])
    np.testing.assert_array_equal(rows, np.ones((4, 8), np.float32))
    assert v2 > v1
    r.close()


def test_revoke_raises_and_overflow_revokes():
    t, ids = seeded_table()
    assert t.shm_export(8 << 20)
    r = ps_shm.open_reader(*t.shm_info())
    t.shm_revoke()
    assert t.shm_info() is None
    with pytest.raises(ps_shm.ShmUnavailable) as ei:
        r.pull(ids[:4])
    assert ei.value.revoked
    r.close()
    # overflow: a mirror sized for ~64 rows dies when the table outgrows
    # it — write-through revokes, the table itself keeps working.
    # (sizing mirrors the worst-case layout math in shm_export: header
    # + 48 index bytes/row + dim*4 row bytes)
    t2, ids2 = seeded_table(n=32, dim=8)
    assert t2.shm_export(4096 + 64 * (8 * 4 + 48))
    r2 = ps_shm.open_reader(*t2.shm_info())
    big = np.arange(1000, 1400, dtype=np.int64)
    t2.push(big, np.ones((400, 8), np.float32))
    with pytest.raises(ps_shm.ShmUnavailable):
        r2.pull(ids2)
    r2.close()


def test_numpy_backend_exports_nothing():
    t = EmbeddingTable(spec(), backend="numpy")
    assert not t.shm_export(8 << 20)
    assert t.shm_info() is None


# ---------------------------------------------------------- client + server
def _cluster(n_shards=2, monkeypatch=None):
    assert monkeypatch is not None
    monkeypatch.setenv("EASYDL_PS_SHM", "1")
    shards = [PsShard(shard_index=i, num_shards=n_shards)
              for i in range(n_shards)]
    servers = [s.serve() for s in shards]
    addrs = [sv.address for sv in servers]
    return shards, servers, addrs


def test_grpc_negotiation_and_bit_parity(monkeypatch):
    shards, servers, addrs = _cluster(monkeypatch=monkeypatch)
    client = ShardedPsClient(addrs, pull_shm=True)
    plain = ShardedPsClient(addrs, pull_shm=False)
    try:
        client.create_table(spec())
        ids = np.arange(300, dtype=np.int64)
        rng = np.random.default_rng(3)
        client.push("emb", ids,
                    rng.standard_normal((300, 8)).astype(np.float32), 0.5)
        client.pull("emb", ids)  # first pull negotiates
        assert client._shm_readers  # segments adopted
        np.testing.assert_array_equal(client.pull("emb", ids),
                                      plain.pull("emb", ids))
        # push-then-read freshness straight through the mirror
        plain.push("emb", ids[:40],
                   rng.standard_normal((40, 8)).astype(np.float32), 0.5)
        np.testing.assert_array_equal(client.pull("emb", ids[:40]),
                                      plain.pull("emb", ids[:40]))
    finally:
        client.close()
        plain.close()
        for sv in servers:
            sv.stop()


def test_cached_read_client_freshness_over_shm(monkeypatch):
    """The PR-9 cache contract holds over the shm transport: a cached
    row tagged with the mirror's version is demoted + re-pulled the
    moment a push bumps it — never served stale."""
    shards, servers, addrs = _cluster(monkeypatch=monkeypatch)
    client = ShardedPsClient(addrs, pull_shm=True)
    plain = ShardedPsClient(addrs, pull_shm=False)
    try:
        client.create_table(spec())
        ids = np.arange(120, dtype=np.int64)
        rng = np.random.default_rng(4)
        plain.push("emb", ids,
                   rng.standard_normal((120, 8)).astype(np.float32), 0.5)
        reads = PsReadClient(client, cache=HotIdCache(4 << 20))
        reads.pull("emb", ids)
        for _ in range(3):
            plain.push("emb", ids[:30],
                       rng.standard_normal((30, 8)).astype(np.float32),
                       0.25)
            np.testing.assert_array_equal(reads.pull("emb", ids),
                                          plain.pull("emb", ids))
        assert reads.counters["demoted"] > 0  # pushes really invalidated
        # quiescent batches: now the cache serves validated hits
        reads.pull("emb", ids)
        reads.pull("emb", ids)
        assert reads.counters["hits"] > 0
    finally:
        client.close()
        plain.close()
        for sv in servers:
            sv.stop()


def test_cutover_fence_and_restore_revoke_mirrors(monkeypatch, tmp_path):
    """Every server-side consistency gate kills the mirror: a cut-over
    reshard source, a restore, and an explicit revoke all force readers
    back to the wire (where stale-route/stale-epoch semantics live)."""
    monkeypatch.setenv("EASYDL_PS_SHM", "1")
    shard = PsShard(shard_index=0, num_shards=1)
    shard.create_table(spec())
    t = shard.table("emb")
    assert t.shm_info() is not None
    reader = ps_shm.open_reader(*t.shm_info())
    shard.cutover()
    assert t.shm_info() is None
    with pytest.raises(ps_shm.ShmUnavailable) as ei:
        reader.pull(np.arange(4, dtype=np.int64))
    assert ei.value.revoked
    reader.close()
    shard.reshard_resume()
    # restore: the fresh table re-exports under a NEW segment; the old
    # one (if any) is revoked explicitly, not left to GC
    shard2 = PsShard(shard_index=0, num_shards=1)
    shard2.create_table(spec())
    shard2.table("emb").push(np.arange(8, dtype=np.int64),
                             np.ones((8, 8), np.float32))
    shard2.save(str(tmp_path / "ck"), step=1)
    old_info = shard2.table("emb").shm_info()
    shard2.restore(str(tmp_path / "ck"))
    new_info = shard2.table("emb").shm_info()
    assert new_info is not None and new_info != old_info


def test_remote_advertisement_falls_back_silently(monkeypatch):
    """A segment name this host cannot open (the remote-shard case) is
    remembered as failed — the client stays on gRPC and keeps working."""
    shards, servers, addrs = _cluster(n_shards=1,
                                      monkeypatch=monkeypatch)
    client = ShardedPsClient(addrs, pull_shm=True)
    try:
        client.create_table(spec())
        ids = np.arange(40, dtype=np.int64)
        client.push("emb", ids, np.ones((40, 8), np.float32), 0.5)
        # sabotage: pretend the shard advertised an alien segment
        t = shards[0].table("emb")
        t._shm = ("/eds-alien-host-segment", 12345)
        out = client.pull("emb", ids)
        assert out.shape == (40, 8)
        assert client._shm_failed  # negotiation failure remembered
        out2 = client.pull("emb", ids)  # still on the wire, still fine
        np.testing.assert_array_equal(out, out2)
    finally:
        client.close()
        for sv in servers:
            sv.stop()


def test_sweep_stale_segments_unlinks_dead_pid_leftovers(tmp_path):
    """A SIGKILLed shard cannot unlink its own mirror — the startup
    sweep removes dead-pid segments and spares live-pid ones."""
    import os

    root = tmp_path / "shm"
    root.mkdir()
    (root / "eds-999999999-deadbeef").write_bytes(b"x")     # dead pid
    (root / f"eds-{os.getpid()}-cafecafe").write_bytes(b"x")  # us: live
    (root / "unrelated-file").write_bytes(b"x")
    assert ps_shm.sweep_stale_segments(str(root)) == 1
    assert sorted(p.name for p in root.iterdir()) == [
        f"eds-{os.getpid()}-cafecafe", "unrelated-file"]


def test_concurrent_push_vs_gather_never_tears(monkeypatch):
    """Seqlock validation: rows imported as all-A or all-B patterns must
    never gather mixed — a torn row would mean the seqlock let a reader
    observe a half-written mirror."""
    import threading

    t, ids = seeded_table(n=64, dim=16)
    assert t.shm_export(8 << 20)
    r = ps_shm.open_reader(*t.shm_info())
    stop = threading.Event()
    patterns = [np.full((64, t.spec.row_width), v, np.float32)
                for v in (1.0, 2.0)]
    t.import_rows(ids, patterns[0])  # start from a known uniform state

    def writer():
        k = 0
        while not stop.is_set():
            t.import_rows(ids, patterns[k % 2])
            k += 1

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    torn = 0
    gathers = 0
    try:
        for _ in range(300):
            try:
                rows, _v = r.pull(ids)
            except ps_shm.ShmUnavailable as e:
                assert not e.revoked
                continue
            gathers += 1
            per_row = rows[:, 0:1]
            uniform = np.all(rows == per_row, axis=1)
            values_ok = np.isin(per_row[:, 0], (1.0, 2.0))
            if not (uniform & values_ok).all():
                torn += 1
    finally:
        stop.set()
        w.join(timeout=10)
    assert gathers > 0
    assert torn == 0
    r.close()
