"""easylint baseline: the committed allowlist for grandfathered findings.

Format — one pipe-separated line per allowlisted finding, sorted, unique::

    rule|path|scope|detail|reason

The reason string is MANDATORY (docs/operations.md): an allowlist entry
without a stated justification is indistinguishable from "we gave up", and
the reviewer of a baseline diff must be able to judge the justification
without archaeology. ``--update-baseline`` preserves existing reasons,
stamps new entries with a TODO marker the gate rejects, and drops stale
entries — so the committed file can only shrink unless a human writes a
reason for the growth.

Matching is a multiset over ``(rule, path, scope, detail)``: the driver
already disambiguates repeated identities (core._disambiguate), so one
baseline line consumes exactly one finding.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from easydl_tpu.analysis.core import Finding

#: Stamped on entries --update-baseline had no reason for; the gate fails
#: while any entry still carries it — baselining requires a human reason.
TODO_REASON = "TODO(easylint): justify this allowlist entry or fix the site"


@dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    scope: str
    detail: str
    reason: str

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.scope, self.detail)

    def render(self) -> str:
        return "|".join((self.rule, self.path, self.scope, self.detail,
                         self.reason))


def parse_line(line: str, lineno: int = 0) -> BaselineEntry:
    parts = line.split("|", 4)
    if len(parts) != 5 or not all(p.strip() for p in parts):
        raise ValueError(
            f"baseline line {lineno}: expected "
            f"'rule|path|scope|detail|reason' with a non-empty reason, "
            f"got {line!r}")
    rule, path, scope, detail, reason = (p.strip() for p in parts)
    return BaselineEntry(rule, path, scope, detail, reason)


def load(path: str) -> List[BaselineEntry]:
    """Missing file == empty baseline (a fresh checkout of a clean tree
    needs no allowlist). Malformed lines raise — a corrupt allowlist must
    not silently admit findings."""
    if not os.path.exists(path):
        return []
    entries: List[BaselineEntry] = []
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entries.append(parse_line(line, i))
    return entries


def save(path: str, entries: Sequence[BaselineEntry]) -> None:
    """Sorted + deduped on write, so baseline diffs stay reviewable no
    matter what order the entries were produced in."""
    lines = sorted({e.render() for e in entries})
    with open(path, "w", encoding="utf-8") as f:
        f.write("# easylint baseline — grandfathered findings. One line per\n"
                "# finding: rule|path|scope|detail|reason. The reason is\n"
                "# mandatory; see docs/operations.md#easylint. Regenerate\n"
                "# with: python scripts/easylint.py --update-baseline\n")
        for line in lines:
            f.write(line + "\n")


def match(findings: Sequence[Finding], entries: Sequence[BaselineEntry],
          ) -> Tuple[List[Finding], List[BaselineEntry]]:
    """Split into (new findings, stale entries). Baselined findings are
    consumed one-for-one; a stale entry means the violation it allowlisted
    is gone and the line should be deleted (run --update-baseline)."""
    budget: Dict[Tuple[str, str, str, str], int] = {}
    for e in entries:
        budget[e.key()] = budget.get(e.key(), 0) + 1
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(f)
    stale: List[BaselineEntry] = []
    for e in entries:  # leftover budget == entries no finding consumed
        if budget.get(e.key(), 0) > 0:
            budget[e.key()] -= 1
            stale.append(e)
    return new, stale


def updated(findings: Sequence[Finding], entries: Sequence[BaselineEntry],
            ) -> List[BaselineEntry]:
    """The --update-baseline merge: every current finding gets an entry,
    reasons carried over from the old baseline where the identity matches,
    TODO-stamped where it does not; stale old entries are dropped."""
    reasons: Dict[Tuple[str, str, str, str], List[str]] = {}
    for e in entries:
        reasons.setdefault(e.key(), []).append(e.reason)
    out: List[BaselineEntry] = []
    for f in findings:
        pool = reasons.get(f.key())
        reason = pool.pop(0) if pool else TODO_REASON
        out.append(BaselineEntry(f.rule, f.path, f.scope, f.detail, reason))
    return out
