"""Controller tests: reconcile decisions (native core + Python twin parity),
job lifecycle (trainer-pod-first), scaling, failure recovery, and
replace-then-retire vertical scaling (SURVEY.md §4 item 4;
docs/design/elastic-training-operator.md:47-55,97-101)."""

import random

import pytest

from easydl_tpu.api.job_spec import JobSpec, ResourceSpec, RoleSpec
from easydl_tpu.api.resource_plan import ResourcePlan, ResourceUpdation, RolePlan
from easydl_tpu.controller import (
    CrStore,
    ElasticJobController,
    InMemoryPodApi,
    Pod,
    reconcile,
    reconcile_wire,
)
from easydl_tpu.controller.reconciler import _SOURCE, _bind, _py_reconcile
from easydl_tpu.utils.native import load_native


def make_job(name="deepctr"):
    return JobSpec(
        name=name, image="easydl:iris", command="python -m model_zoo.iris",
        roles={"worker": RoleSpec(), "parameter_server": RoleSpec()},
    )


def make_plan(job="deepctr", ps=1, workers=2, version=1, updations=()):
    return ResourcePlan(
        name=f"{job}-plan", job_name=job, version=version,
        roles={
            "parameter_server": RolePlan(ps, ResourceSpec(cpu=4, memory=4096)),
            "worker": RolePlan(workers, ResourceSpec(cpu=8, memory=8192)),
        },
        resource_updation=list(updations),
    )


# ----------------------------------------------------------------- decision


def test_native_core_builds():
    assert load_native(_SOURCE, _bind) is not None


def test_native_python_parity_randomized():
    """The C++ core and its Python twin must make identical decisions on
    randomized cluster states."""
    rng = random.Random(0)
    phases = ["Pending", "Running", "Succeeded", "Failed", "Terminating"]
    for trial in range(200):
        job = "j"
        n_pods = rng.randint(0, 8)
        observed_lines = []
        names = set()
        for i in range(n_pods):
            role = rng.choice(["worker", "parameter_server"])
            name = f"{job}-{role}-{rng.randint(0, 9)}"
            if name in names:
                continue
            names.add(name)
            replaces = rng.choice(["", *names - {name}]) if rng.random() < 0.3 else ""
            observed_lines.append(
                f"P|{name}|{role}|{rng.choice(phases)}|sig{rng.randint(0,2)}|{replaces}"
            )
        desired_lines = [f"J|{job}"]
        for role in ("worker", "parameter_server"):
            if rng.random() < 0.9:
                # Mostly valid counts, sometimes malformed (empty, signed,
                # spaced, junk) — both implementations must skip malformed
                # R-lines identically instead of atoi-vs-int() diverging.
                replicas = rng.choice(
                    [str(rng.randint(0, 5))] * 4
                    + ["", "-1", "+2", " 3", "2x", "x2", "99999999999"]
                )
                desired_lines.append(f"R|{role}|{replicas}|sig0")
        for name in list(names)[:2]:
            if rng.random() < 0.4:
                desired_lines.append(f"U|{name}|sig9")
        desired = "\n".join(desired_lines) + "\n"
        observed = "".join(line + "\n" for line in observed_lines)
        native = reconcile_wire(desired, observed)
        python = _py_reconcile(desired, observed)
        assert native == python, (
            f"trial {trial}: core/twin divergence\n"
            f"desired:\n{desired}observed:\n{observed}"
            f"native:\n{native}python:\n{python}"
        )


# ---------------------------------------------------------------- lifecycle


def test_trainer_pod_first():
    """Figure steps 1-3: job submission creates ONLY the trainer pod."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    ctl.step(timeout=1)
    pods = api.list_pods("deepctr")
    assert [p.name for p in pods] == ["deepctr-trainer-0"]
    assert pods[0].command == "python -m model_zoo.iris"


def test_plan_creates_role_pods():
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    ctl.step(timeout=1)
    store.apply_plan(make_plan(ps=1, workers=2))
    ctl.step(timeout=1)
    roles = sorted((p.role, p.name) for p in api.list_pods("deepctr"))
    assert roles == [
        ("parameter_server", "deepctr-parameter_server-0"),
        ("trainer", "deepctr-trainer-0"),
        ("worker", "deepctr-worker-0"),
        ("worker", "deepctr-worker-1"),
    ]
    # pods carry the plan's resources
    w = api.get_pod("deepctr-worker-0")
    assert w.resource.cpu == 8 and w.resource.memory == 8192


def test_scale_up_and_down():
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(workers=2))
    ctl.reconcile_job("deepctr")
    api.tick()  # all Running
    store.apply_plan(make_plan(workers=4, version=2))
    ctl.reconcile_job("deepctr")
    workers = [p for p in api.list_pods("deepctr") if p.role == "worker"]
    assert len(workers) == 4
    store.apply_plan(make_plan(workers=1, version=3))
    ctl.reconcile_job("deepctr")
    workers = [p for p in api.list_pods("deepctr") if p.role == "worker"]
    # highest indices retired first
    assert [p.name for p in workers] == ["deepctr-worker-0"]


def test_failed_pod_recovered_with_fresh_name():
    """README.md:26-29: failed workers are recovered; names never reused."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(workers=2))
    ctl.reconcile_job("deepctr")
    api.tick()
    api.fail("deepctr-worker-0")
    ctl.reconcile_job("deepctr")
    workers = sorted(p.name for p in api.list_pods("deepctr") if p.role == "worker")
    assert workers == ["deepctr-worker-1", "deepctr-worker-2"]


def test_malformed_replicas_freezes_role_instead_of_scaling_to_zero():
    """A corrupt replicas field must leave the role untouched — neither
    atoi's silent 0 nor the absent-role fallback may delete healthy pods."""
    observed = (
        "P|j-worker-0|worker|Running|sig0|\n"
        "P|j-worker-1|worker|Running|sig0|\n"
    )
    for desired in ("J|j\nR|worker|2x|sig0\n", "J|j\nR|worker||sig0\n",
                    "J|j\nR|worker| 2|sig0\n", "J|j\nR|worker|-1|sig0\n",
                    # all-digits but >7 digits: would overflow atoi (UB) /
                    # explode the Python levelling loop — frozen too
                    "J|j\nR|worker|4294967294|sig0\n"):
        native = reconcile_wire(desired, observed)
        python = _py_reconcile(desired, observed)
        assert native == python == "", (desired, native, python)


def test_crash_loop_backs_off_but_first_failure_recovers_instantly():
    """A single failure must be replaced in the same pass (recovery time is
    a headline metric); repeated failures must NOT hot-respawn every pass —
    the operator defers creates exponentially until a quiet window passes."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(
        store, api,
        restart_backoff_base=30.0,   # big, so the deferral is observable
        restart_backoff_reset=0.2,   # small, so the test can see forgiveness
    )
    store.submit_job(make_job())
    store.apply_plan(make_plan(workers=1))
    ctl.reconcile_job("deepctr")
    api.tick()

    # failure 1: replaced immediately, same pass
    api.fail("deepctr-worker-0")
    ctl.reconcile_job("deepctr")
    names = [p.name for p in api.list_pods("deepctr") if p.role == "worker"]
    assert names == ["deepctr-worker-1"]
    api.tick()

    # failure 2 (within the reset window): create deferred
    api.fail("deepctr-worker-1")
    ctl.reconcile_job("deepctr")
    assert [p for p in api.list_pods("deepctr") if p.role == "worker"] == []
    # ... and keeps deferring on hot re-reconciles
    ctl.reconcile_job("deepctr")
    assert [p for p in api.list_pods("deepctr") if p.role == "worker"] == []

    # after a quiet window the role is forgiven: next failure is "first"
    import time as _time

    _time.sleep(0.25)
    ctl._note_failure("deepctr", "worker")  # counts as fresh failure (count 1)
    ctl.reconcile_job("deepctr")
    workers = [p for p in api.list_pods("deepctr") if p.role == "worker"]
    assert len(workers) == 1  # recovered instantly again after quiet window


def test_replace_then_retire():
    """docs/design/elastic-training-operator.md:99-101: the replacement pod
    launches first; the old pod is retired only once it's Running."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=2, workers=1))
    ctl.reconcile_job("deepctr")
    api.tick()

    upd = ResourceUpdation("deepctr-parameter_server-0", ResourceSpec(cpu=16, memory=16384))
    store.apply_plan(make_plan(ps=2, workers=1, version=2, updations=[upd]))
    ctl.reconcile_job("deepctr")
    ps = {p.name: p for p in api.list_pods("deepctr") if p.role == "parameter_server"}
    # replacement created (Pending), old still present and serving
    assert len(ps) == 3
    rep = next(p for p in ps.values() if p.replaces == "deepctr-parameter_server-0")
    assert rep.phase == "Pending" and rep.resource.cpu == 16
    assert ps["deepctr-parameter_server-0"].phase == "Running"

    # a second pass while the replacement is still Pending must not create
    # another replacement (idempotence)
    ctl.reconcile_job("deepctr")
    assert len([p for p in api.list_pods("deepctr") if p.role == "parameter_server"]) == 3

    api.tick()  # replacement becomes Running
    ctl.reconcile_job("deepctr")
    ps_after = [p for p in api.list_pods("deepctr") if p.role == "parameter_server"]
    names = sorted(p.name for p in ps_after)
    assert "deepctr-parameter_server-0" not in names and len(ps_after) == 2
    # steady state: nothing more to do
    ctl.reconcile_job("deepctr")
    assert len([p for p in api.list_pods("deepctr") if p.role == "parameter_server"]) == 2


def test_replace_then_retire_graceful_no_churn():
    """With graceful deletion the retired pod lingers Terminating; the
    running replacement owns the slot — no spurious extra pod may appear."""
    store, api = CrStore(), InMemoryPodApi(graceful=True)
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=1, workers=1))
    ctl.reconcile_job("deepctr")
    api.tick()
    upd = ResourceUpdation("deepctr-parameter_server-0", ResourceSpec(cpu=16))
    store.apply_plan(make_plan(ps=1, workers=1, version=2, updations=[upd]))
    ctl.reconcile_job("deepctr")
    api.tick()  # replacement Running
    ctl.reconcile_job("deepctr")  # retires old ps-0 -> Terminating
    old = api.get_pod("deepctr-parameter_server-0")
    assert old is not None and old.phase == "Terminating"
    ctl.reconcile_job("deepctr")  # must NOT create a third ps pod
    ps = [p for p in api.list_pods("deepctr") if p.role == "parameter_server"]
    assert sorted(p.phase for p in ps) == ["Running", "Terminating"]


def test_role_omitted_from_plan_scales_to_zero():
    """Dropping a role key from a newer plan means replicas 0 — its pods
    must be retired, not orphaned."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    plan = make_plan(ps=1, workers=2)
    plan.roles["evaluator"] = RolePlan(2, ResourceSpec(cpu=2))
    store.apply_plan(plan)
    ctl.reconcile_job("deepctr")
    api.tick()
    assert len([p for p in api.list_pods("deepctr") if p.role == "evaluator"]) == 2
    store.apply_plan(make_plan(ps=1, workers=2, version=2))  # no evaluator key
    ctl.reconcile_job("deepctr")
    assert [p for p in api.list_pods("deepctr") if p.role == "evaluator"] == []
    # trainer is exempt from absent-role scale-down
    assert [p.role for p in api.list_pods("deepctr") if p.role == "trainer"]


def test_failed_trainer_recreated_fresh_name():
    """A trainer crash before any plan exists must not strand the job; the
    replacement gets a fresh index."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    ctl.reconcile_job("deepctr")
    api.tick()
    api.fail("deepctr-trainer-0")
    ctl.reconcile_job("deepctr")
    trainers = [p for p in api.list_pods("deepctr") if p.role == "trainer"]
    assert [p.name for p in trainers] == ["deepctr-trainer-1"]
    assert trainers[0].phase == "Pending"


def test_stale_plan_rejected():
    store, api = CrStore(), InMemoryPodApi()
    store.submit_job(make_job())
    store.apply_plan(make_plan(version=2))
    with pytest.raises(ValueError, match="stale"):
        store.apply_plan(make_plan(version=2))
    with pytest.raises(KeyError):
        store.apply_plan(make_plan(job="nosuch", version=1))


def test_job_deletion_tears_down_pods():
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan())
    ctl.reconcile_job("deepctr")
    assert api.list_pods("deepctr")
    store.delete_job("deepctr")
    ctl.reconcile_job("deepctr")
    assert api.list_pods("deepctr") == []


def test_example_manifests_parse():
    """The shipped manifests/examples must round-trip through the API
    contracts (schema drift between manifests/ and api/ fails here)."""
    import glob
    import os

    import yaml

    root = os.path.join(os.path.dirname(__file__), "..", "manifests", "examples")
    docs = []
    for path in sorted(glob.glob(os.path.join(root, "*.yaml"))):
        with open(path) as f:
            docs.extend(d for d in yaml.safe_load_all(f) if isinstance(d, dict))
    assert docs, "no example manifests found"
    kinds = set()
    runner_prefix = "python -m easydl_tpu.models.run "
    for doc in docs:
        if doc["kind"] == "ElasticJob":
            job = JobSpec.from_crd(doc)
            job.validate()
            # the entry command's flags must be accepted by the zoo runner
            # (example-vs-CLI drift crashloops every pod)
            if job.command.startswith(runner_prefix):
                from easydl_tpu.models.run import build_parser

                argv = job.command[len(runner_prefix):].split()
                build_parser().parse_args(argv)  # SystemExit on bad flags
        elif doc["kind"] == "JobResource":
            plan = ResourcePlan.from_crd(doc)
            plan.validate()
            assert plan.total_tpu_chips > 0  # the TPU example demands chips
        kinds.add(doc["kind"])
    assert kinds == {"ElasticJob", "JobResource"}


def test_background_controller_converges():
    """Event-driven loop: submit → plan → pod failure, all absorbed without
    manual reconcile calls."""
    import time

    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    api.watch(lambda verb, name: store.poke("deepctr") if verb == "failed" else None)
    ctl.start(resync_s=0.05)
    try:
        store.submit_job(make_job())
        store.apply_plan(make_plan(workers=3))
        deadline = time.time() + 5
        while time.time() < deadline:
            if len([p for p in api.list_pods("deepctr") if p.role == "worker"]) == 3:
                break
            time.sleep(0.02)
        api.tick()
        api.fail("deepctr-worker-1")
        while time.time() < deadline:
            live = [
                p for p in api.list_pods("deepctr")
                if p.role == "worker" and p.phase in ("Pending", "Running")
            ]
            if len(live) == 3 and "deepctr-worker-1" not in {p.name for p in live}:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("controller did not recover failed worker")
    finally:
        ctl.stop()


# ------------------------------------------------------- terminal job state


def test_succeeded_worker_slot_not_refilled():
    """A pod that exits 0 completed its work (k8s Job semantics): the slot
    is filled forever — recreating it would re-run 'job done' in a loop
    (the round-3 completion-loop defect)."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=0, workers=2))
    ctl.reconcile_job("deepctr")
    api.tick()
    api.set_phase("deepctr-worker-0", "Succeeded")
    ctl.reconcile_job("deepctr")
    workers = [p for p in api.list_pods("deepctr") if p.role == "worker"]
    # no replacement created; the Succeeded record is retained, not deleted
    assert sorted(p.name for p in workers) == [
        "deepctr-worker-0", "deepctr-worker-1"
    ]
    assert api.get_pod("deepctr-worker-0").phase == "Succeeded"
    # but a FAILED pod is still replaced (elasticity is untouched)
    api.fail("deepctr-worker-1")
    ctl.reconcile_job("deepctr")
    live = [p for p in api.list_pods("deepctr")
            if p.role == "worker" and p.phase in ("Pending", "Running")]
    assert [p.name for p in live] == ["deepctr-worker-2"]


def test_trainer_success_latches_job_terminal():
    """Trainer pod Succeeded = job complete: no trainer recreation, no
    levelling, still-live service pods GC'd, status written — and the state
    is STABLE across arbitrarily many reconcile passes."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=1, workers=2))
    ctl.reconcile_job("deepctr")
    api.tick()
    # workers finish, then the trainer exits 0
    api.set_phase("deepctr-worker-0", "Succeeded")
    api.set_phase("deepctr-worker-1", "Succeeded")
    api.set_phase("deepctr-trainer-0", "Succeeded")
    st = ctl.reconcile_job("deepctr")
    assert st.phase == "Succeeded"
    # the PS pod never exits on its own: completion GC deletes it
    assert api.get_pod("deepctr-parameter_server-0") is None
    names = {p.name for p in api.list_pods("deepctr")}
    # two more passes create/delete NOTHING (the round-3 loop is gone)
    for _ in range(3):
        st = ctl.reconcile_job("deepctr")
        assert st.phase == "Succeeded"
        assert not any(op.startswith(("CREATE", "DELETE"))
                       for op in st.last_ops), st.last_ops
    assert {p.name for p in api.list_pods("deepctr")} == names
    status = store.job_status("deepctr")
    assert status["phase"] == "Succeeded"
    assert status["completionTime"]
    assert status["roles"]["worker"]["succeeded"] == 2
    # a newer plan cannot resurrect a finished job
    store.apply_plan(make_plan(ps=2, workers=4, version=2))
    ctl.reconcile_job("deepctr")
    assert {p.name for p in api.list_pods("deepctr")} == names


def test_terminal_latch_survives_operator_restart():
    """The latch lives in ElasticJob.status, not operator memory: a fresh
    controller fed the stored status keeps a finished job finished even if
    the trainer pod record was GC'd externally."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=0, workers=1))
    ctl.reconcile_job("deepctr")
    api.tick()
    api.set_phase("deepctr-worker-0", "Succeeded")
    api.set_phase("deepctr-trainer-0", "Succeeded")
    ctl.reconcile_job("deepctr")
    saved_status = store.job_status("deepctr")
    assert saved_status["phase"] == "Succeeded"
    # "restart": new store + controller; pods GC'd externally; only the
    # ElasticJob spec + status survive (as they would on the API server)
    store2, api2 = CrStore(), InMemoryPodApi()
    store2.submit_job(make_job())
    store2.set_status("deepctr", saved_status)
    ctl2 = ElasticJobController(store2, api2)
    st = ctl2.reconcile_job("deepctr")
    assert st.phase == "Succeeded"
    assert api2.list_pods("deepctr") == []  # nothing recreated


def test_status_terminal_phase_cannot_unlatch():
    store = CrStore()
    store.submit_job(make_job())
    assert store.set_status("deepctr", {"phase": "Succeeded", "roles": {}})
    assert not store.set_status("deepctr", {"phase": "Running", "roles": {}})
    assert not store.set_status("deepctr", {"phase": "Failed", "roles": {}})
    assert store.job_status("deepctr")["phase"] == "Succeeded"
    # same-phase refresh (counts after GC) is allowed
    assert store.set_status(
        "deepctr", {"phase": "Succeeded", "roles": {"worker": {"active": 0}}}
    )


def test_slow_status_sink_does_not_stall_set_status():
    """Verdict r4 #8b: sinks fire on the dispatch thread, so a slow API
    server (sink) can't stall the reconcile loop's status writes. Pending
    writes coalesce — the sink always ends on the LATEST document."""
    import threading
    import time as _time

    store = CrStore()
    store.submit_job(make_job())
    seen, release = [], threading.Event()

    def slow_sink(job, status):
        release.wait(5.0)
        seen.append(status["phase"])

    store.add_status_sink(slow_sink)
    t0 = _time.monotonic()
    store.set_status("deepctr", {"phase": "Pending", "roles": {}})
    store.set_status("deepctr", {"phase": "Running", "roles": {}})
    elapsed = _time.monotonic() - t0
    assert elapsed < 1.0, f"set_status blocked {elapsed:.2f}s on the sink"
    release.set()
    assert store.flush_status()
    assert seen[-1] == "Running"
    store.close()


def test_status_sink_failure_marks_dirty_and_retries():
    """An async sink failure still marks the status dirty, so the next
    identical write (the operator's resync) re-fires the sink."""
    store = CrStore()
    store.submit_job(make_job())
    calls = {"n": 0}

    def flaky_sink(job, status):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("API server blip")

    store.add_status_sink(flaky_sink)
    status = {"phase": "Running", "roles": {}}
    assert store.set_status("deepctr", status)
    assert store.flush_status()
    assert calls["n"] == 1
    # identical write: normally a no-op, but the dirty mark re-fires sinks
    assert not store.set_status("deepctr", dict(status))
    assert store.flush_status()
    assert calls["n"] == 2
    store.close()


def test_trainer_backoff_limit_fails_job():
    """k8s Job backoffLimit analogue: a crash-looping trainer eventually
    latches the job Failed instead of restarting forever."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(
        store, api, trainer_backoff_limit=2,
        restart_backoff_base=0.0, restart_backoff_max=0.0,
    )
    store.submit_job(make_job())
    for _ in range(4):
        ctl.reconcile_job("deepctr")
        api.tick()
        trainers = [p for p in api.list_pods("deepctr")
                    if p.role == "trainer" and p.phase == "Running"]
        if not trainers:
            break
        api.fail(trainers[0].name)
    st = ctl.reconcile_job("deepctr")
    assert st.phase == "Failed"
    assert store.job_status("deepctr")["phase"] == "Failed"
    assert "restart limit" in store.job_status("deepctr")["message"]
    # stable: no new trainer appears on later passes
    ctl.reconcile_job("deepctr")
    assert not any(p.phase in ("Pending", "Running")
                   for p in api.list_pods("deepctr"))


def test_running_status_reported():
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    ctl.reconcile_job("deepctr")
    assert store.job_status("deepctr")["phase"] == "Pending"
    api.tick()
    store.apply_plan(make_plan(ps=1, workers=2))
    ctl.reconcile_job("deepctr")
    status = store.job_status("deepctr")
    assert status["phase"] == "Running"
    assert status["roles"]["worker"]["active"] == 2


def test_updation_on_succeeded_pod_is_inert():
    """A resource_updation targeting a Succeeded pod must neither replace it
    (re-running finished work) nor churn create/delete cycles."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api)
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=0, workers=1))
    ctl.reconcile_job("deepctr")
    api.tick()
    api.set_phase("deepctr-worker-0", "Succeeded")
    from easydl_tpu.api.resource_plan import ResourceUpdation as RU
    store.apply_plan(make_plan(
        ps=0, workers=1, version=2,
        updations=[RU(name="deepctr-worker-0", resource=ResourceSpec(cpu=16))],
    ))
    for _ in range(3):
        st = ctl.reconcile_job("deepctr")
        assert not any(op.startswith(("CREATE deepctr-worker",
                                      "DELETE deepctr-worker"))
                       for op in st.last_ops), st.last_ops
    assert api.get_pod("deepctr-worker-0").phase == "Succeeded"


def test_trainer_backoff_limit_counts_real_failures():
    """With real (nonzero) backoff, each trainer crash counts exactly once
    toward the limit — the deferred-recreate path must not double-count via
    the plan reconcile seeing the stale Failed pod."""
    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(
        store, api, trainer_backoff_limit=4,
        restart_backoff_base=0.05, restart_backoff_max=0.05,
    )
    store.submit_job(make_job())
    store.apply_plan(make_plan(ps=0, workers=1))
    fails = 0
    import time as _t
    deadline = _t.monotonic() + 20
    while fails < 4 and _t.monotonic() < deadline:
        ctl.reconcile_job("deepctr")
        api.tick()
        live = [p for p in api.list_pods("deepctr")
                if p.role == "trainer" and p.phase == "Running"]
        if live:
            api.fail(live[0].name)
            fails += 1
            # extra reconcile passes while the recreate is deferred: these
            # see the stale state and must NOT inflate the failure count
            ctl.reconcile_job("deepctr")
            ctl.reconcile_job("deepctr")
            _t.sleep(0.06)
    assert fails == 4
    st = ctl.reconcile_job("deepctr")
    # exactly at the limit: not exceeded yet, job still live
    assert st.phase != "Failed", store.job_status("deepctr")
    # the 5th consecutive failure crosses the limit
    api.tick()
    live = [p for p in api.list_pods("deepctr")
            if p.role == "trainer" and p.phase == "Running"]
    assert live
    api.fail(live[0].name)
    st = ctl.reconcile_job("deepctr")
    assert st.phase == "Failed"


def test_terminal_gc_grants_evaluator_grace():
    """At the terminal latch a Running evaluator is mid-final-eval and exits
    0 on its own; GC must wait out a grace window for it (killing it there
    would lose the final-step evaluation), while the PS is GC'd at once."""
    import time

    def eval_job():
        return JobSpec(
            name="deepctr", image="easydl:iris",
            command="python -m model_zoo.iris",
            roles={"worker": RoleSpec(), "parameter_server": RoleSpec(),
                   "evaluator": RoleSpec()},
        )

    store, api = CrStore(), InMemoryPodApi()
    ctl = ElasticJobController(store, api, evaluator_gc_grace_s=0.4)
    store.submit_job(eval_job())
    plan = make_plan(ps=1, workers=1)
    plan.roles["evaluator"] = RolePlan(1, ResourceSpec(cpu=4, memory=4096))
    store.apply_plan(plan)
    ctl.reconcile_job("deepctr")
    api.tick()
    api.set_phase("deepctr-worker-0", "Succeeded")
    api.set_phase("deepctr-trainer-0", "Succeeded")
    st = ctl.reconcile_job("deepctr")
    assert st.phase == "Succeeded"
    # PS gone immediately; evaluator still running inside the grace window
    assert api.get_pod("deepctr-parameter_server-0") is None
    assert api.get_pod("deepctr-evaluator-0").phase == "Running"
    # it finishes by itself -> retained as Succeeded, never deleted
    api.set_phase("deepctr-evaluator-0", "Succeeded")
    ctl.reconcile_job("deepctr")
    assert api.get_pod("deepctr-evaluator-0").phase == "Succeeded"
    # a WEDGED evaluator is reaped once the grace expires
    store2, api2 = CrStore(), InMemoryPodApi()
    ctl2 = ElasticJobController(store2, api2, evaluator_gc_grace_s=0.1)
    store2.submit_job(eval_job())
    store2.apply_plan(plan)
    ctl2.reconcile_job("deepctr")
    api2.tick()
    api2.set_phase("deepctr-worker-0", "Succeeded")
    api2.set_phase("deepctr-trainer-0", "Succeeded")
    ctl2.reconcile_job("deepctr")
    assert api2.get_pod("deepctr-evaluator-0").phase == "Running"
    time.sleep(0.15)
    ctl2.reconcile_job("deepctr")
    assert api2.get_pod("deepctr-evaluator-0") is None


def test_pod_api_shutdown_reaps_mid_spawn_creates(tmp_path, monkeypatch):
    """Regression: create_pod spawns OUTSIDE the table lock (easylint's
    blocking-call-under-lock fix); a shutdown()/delete_pod() landing in
    that window must still cover the child — the late registration kills
    it instead of leaking it past teardown."""
    import subprocess as _subprocess
    import time as _time

    from easydl_tpu.controller.pod_api import Pod
    from easydl_tpu.controller import process_pod_api as mod

    api = mod.LocalProcessPodApi(str(tmp_path))
    real_popen = _subprocess.Popen
    spawned = {}

    def popen_with_race(*args, **kwargs):
        proc = real_popen(*args, **kwargs)
        spawned["proc"] = proc
        api.delete_pod("racer")  # lands while the name is only _pending
        return proc

    monkeypatch.setattr(mod.subprocess, "Popen", popen_with_race)
    api.create_pod(Pod(name="racer", role="worker", job="j",
                       command="sleep 30"))
    # not registered, and the child did not leak
    assert api.list_pods() == []
    deadline = _time.monotonic() + 5
    while spawned["proc"].poll() is None and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert spawned["proc"].poll() is not None, "mid-spawn child leaked"

    # after shutdown(), create_pod refuses outright
    monkeypatch.setattr(mod.subprocess, "Popen", real_popen)
    api.shutdown()
    with pytest.raises(ValueError):
        api.create_pod(Pod(name="late", role="worker", job="j",
                           command="sleep 30"))
