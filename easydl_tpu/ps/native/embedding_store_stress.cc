// Concurrency stress driver for the embedding store, compiled with
// TSan/ASan by scripts/sanitize_native.sh (SURVEY.md §5.2). Includes the
// store's translation unit directly so the sanitizer instruments the real
// code, then hammers the concurrent surface the gRPC shard exposes: many
// threads pulling/pushing overlapping id ranges while another exports for
// checkpointing.

#include "embedding_store.cc"  // NOLINT(build/include)

#include <atomic>
#include <cassert>
#include <cstdio>
#include <thread>
#include <vector>

namespace {

constexpr int kThreads = 8;
constexpr int kIters = 400;
constexpr int kDim = 16;
constexpr int64_t kIds = 512;  // small id space: maximal contention

void worker(void* store, int seed, std::atomic<bool>* stop) {
  uint64_t rng = static_cast<uint64_t>(seed) * 0x9e3779b97f4a7c15ULL + 1;
  std::vector<int64_t> ids(32);
  std::vector<float> buf(ids.size() * kDim, 0.25f);
  for (int it = 0; it < kIters && !stop->load(); ++it) {
    for (auto& id : ids) {
      rng = splitmix64(rng);
      id = static_cast<int64_t>(rng % kIds);
    }
    if (it % 3 == 0) {
      eds_push(store, ids.data(), static_cast<int64_t>(ids.size()),
               buf.data(), 0.5f);
    } else {
      eds_pull(store, ids.data(), static_cast<int64_t>(ids.size()),
               buf.data());
    }
  }
}

void exporter(void* store, std::atomic<bool>* stop) {
  while (!stop->load()) {
    int64_t n = eds_size(store);
    if (n > 0) {
      std::vector<int64_t> ids(static_cast<size_t>(n) + 64);
      std::vector<float> rows(ids.size() * 2 * kDim);
      int64_t written = eds_export(store, ids.data(), rows.data(),
                                   static_cast<int64_t>(ids.size()));
      assert(written <= static_cast<int64_t>(ids.size()));
    }
  }
}

}  // namespace

int main() {
  void* store = eds_create(kDim, 0.01f, 7, /*adagrad=*/1, 0.05f, 1e-8f);
  std::atomic<bool> stop{false};
  std::vector<std::thread> threads;
  threads.emplace_back(exporter, store, &stop);
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back(worker, store, t, &stop);
  }
  for (size_t t = 1; t < threads.size(); ++t) threads[t].join();
  stop.store(true);
  threads[0].join();
  const int64_t rows = eds_size(store);
  assert(rows > 0 && rows <= kIds);
  std::printf("stress OK: %lld rows\n", static_cast<long long>(rows));
  eds_destroy(store);
  return 0;
}
