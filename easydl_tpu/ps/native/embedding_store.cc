// Host-side sparse embedding store — the native core of the parameter-server
// role (reference: PS role, docs/design/elastic-training-operator.md:39-40;
// the reference anticipates C++ sources via its clang-format/cpplint hooks,
// .pre-commit-config.yaml:24-41, but ships none — this is the TPU-native
// equivalent: dense math stays on TPU, huge embedding tables stay in host
// DRAM behind pull/push).
//
// Design:
//   * lock-striped: 64 stripes, each an open hash map id -> row offset into a
//     per-stripe arena. Pull/push from many gRPC threads proceed in parallel
//     unless they hit the same stripe.
//   * lazy deterministic init: a row materialises on first touch with values
//     drawn from splitmix64(seed ^ id) — the same id yields the same row on
//     any shard layout, which is what makes PS resharding trivial.
//   * sparse optimizers: SGD and Adagrad. Push accumulates duplicate ids
//     first, then applies ONE optimizer step per unique id — matching what a
//     dense scatter-add gradient would do on device.
//   * export/import for checkpointing: rows travel with their ids, so a
//     restore can filter by any new shard count (reshard-on-restore for the
//     PS tier, mirroring easydl_tpu/core/checkpoint.py for the dense tier).
//
// Exposed as a C ABI (eds_*) consumed via ctypes from
// easydl_tpu/ps/table.py; no pybind11 in this image.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumStripes = 64;  // power of two

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

inline int stripe_of(int64_t id) {
  // Double-hash: shard routing uses splitmix64(id) % num_shards
  // (easydl_tpu/ps/table.py shard_of), so one shard's ids share a residue of
  // that hash — hashing again decorrelates striping from routing (otherwise
  // e.g. num_shards=64 would funnel every id on a shard into ONE stripe).
  return static_cast<int>(
      splitmix64(splitmix64(static_cast<uint64_t>(id))) & (kNumStripes - 1));
}

// Optimizer kinds (keep in sync with easydl_tpu/ps/table.py).
enum Optimizer : int { kSgd = 0, kAdagrad = 1 };

struct Stripe {
  std::mutex mu;
  std::unordered_map<int64_t, size_t> index;  // id -> offset into arena
  std::vector<float> arena;                   // row_width floats per row
};

class EmbeddingStore {
 public:
  EmbeddingStore(int dim, float init_std, uint64_t seed, int optimizer,
                 float lr, float eps)
      : dim_(dim),
        init_std_(init_std),
        seed_(seed),
        optimizer_(optimizer),
        lr_(lr),
        eps_(eps),
        row_width_(optimizer == kAdagrad ? 2 * dim : dim) {}

  int dim() const { return dim_; }
  int row_width() const { return row_width_; }

  // out: [n, dim] row-major.
  void Pull(const int64_t* ids, int64_t n, float* out) {
    std::shared_lock<std::shared_mutex> snap(SharedBarrier());
    for (int64_t i = 0; i < n; ++i) {
      Stripe& s = stripes_[stripe_of(ids[i])];
      std::lock_guard<std::mutex> lock(s.mu);
      float* row = FindOrInit(&s, ids[i]);
      std::memcpy(out + i * dim_, row, sizeof(float) * dim_);
    }
  }

  // grads: [n, dim] row-major; duplicate ids are accumulated before the
  // optimizer applies, and `scale` multiplies the accumulated gradient.
  void Push(const int64_t* ids, int64_t n, const float* grads, float scale) {
    std::shared_lock<std::shared_mutex> snap(SharedBarrier());
    std::unordered_map<int64_t, size_t> first;
    first.reserve(static_cast<size_t>(n));
    std::vector<int64_t> uniq;
    std::vector<float> acc;
    for (int64_t i = 0; i < n; ++i) {
      auto it = first.find(ids[i]);
      size_t slot;
      if (it == first.end()) {
        slot = uniq.size();
        first.emplace(ids[i], slot);
        uniq.push_back(ids[i]);
        acc.insert(acc.end(), grads + i * dim_, grads + (i + 1) * dim_);
      } else {
        slot = it->second;
        float* dst = acc.data() + slot * dim_;
        const float* src = grads + i * dim_;
        for (int d = 0; d < dim_; ++d) dst[d] += src[d];
      }
    }
    for (size_t u = 0; u < uniq.size(); ++u) {
      Stripe& s = stripes_[stripe_of(uniq[u])];
      std::lock_guard<std::mutex> lock(s.mu);
      float* row = FindOrInit(&s, uniq[u]);
      const float* g = acc.data() + u * dim_;
      ApplyUpdate(row, g, scale);
    }
  }

  int64_t Size() {
    int64_t total = 0;
    for (auto& s : stripes_) {
      std::lock_guard<std::mutex> lock(s.mu);
      total += static_cast<int64_t>(s.index.size());
    }
    return total;
  }

  // ids_out: [capacity]; rows_out: [capacity, row_width]. Returns rows
  // written (<= capacity). Takes the snapshot barrier exclusively, so the
  // exported rows form a point-in-time snapshot even while workers keep
  // pulling/pushing from other threads: no row in a single export straddles
  // an optimizer step, and the export is complete whenever
  // capacity >= Size() sampled under the same barrier (see SizeLocked use in
  // eds_export_snapshot).
  int64_t Export(int64_t* ids_out, float* rows_out, int64_t capacity) {
    ExclusiveBarrier snap(this);
    return ExportLocked(ids_out, rows_out, capacity);
  }

  int64_t ExportLocked(int64_t* ids_out, float* rows_out, int64_t capacity) {
    int64_t w = 0;
    for (auto& s : stripes_) {
      std::lock_guard<std::mutex> lock(s.mu);
      for (const auto& kv : s.index) {
        if (w >= capacity) return w;
        ids_out[w] = kv.first;
        std::memcpy(rows_out + w * row_width_, s.arena.data() + kv.second,
                    sizeof(float) * row_width_);
        ++w;
      }
    }
    return w;
  }

  // Consistent size+export in one critical section: writes at most
  // `capacity` rows and stores the table's true size (sampled under the
  // exclusive barrier) in *size_out, so the caller can detect truncation
  // and retry with a larger buffer.
  int64_t ExportSnapshot(int64_t* ids_out, float* rows_out, int64_t capacity,
                         int64_t* size_out) {
    ExclusiveBarrier snap(this);
    int64_t total = 0;
    for (auto& s : stripes_) {
      std::lock_guard<std::mutex> lock(s.mu);
      total += static_cast<int64_t>(s.index.size());
    }
    if (size_out != nullptr) *size_out = total;
    return ExportLocked(ids_out, rows_out, capacity);
  }

  // rows: [n, row_width]; inserts or overwrites.
  void Import(const int64_t* ids, const float* rows, int64_t n) {
    std::shared_lock<std::shared_mutex> snap(SharedBarrier());
    for (int64_t i = 0; i < n; ++i) {
      Stripe& s = stripes_[stripe_of(ids[i])];
      std::lock_guard<std::mutex> lock(s.mu);
      float* row = FindOrAlloc(&s, ids[i]);
      std::memcpy(row, rows + i * row_width_, sizeof(float) * row_width_);
    }
  }

 private:
  // Deterministic per-id row init: values uniform in [-a, a] with
  // a = init_std * sqrt(3) (variance init_std^2), from splitmix64 — bit-exact
  // match with the numpy fallback in easydl_tpu/ps/table.py.
  void InitRow(int64_t id, float* row) {
    const uint64_t base = splitmix64(seed_ ^ static_cast<uint64_t>(id));
    const float a = init_std_ * 1.7320508075688772f;
    for (int d = 0; d < dim_; ++d) {
      const uint64_t bits = splitmix64(base + static_cast<uint64_t>(d));
      // Top 24 bits -> uniform [0, 1).
      const float u =
          static_cast<float>(bits >> 40) * (1.0f / 16777216.0f);
      row[d] = (2.0f * u - 1.0f) * a;
    }
    for (int d = dim_; d < row_width_; ++d) row[d] = 0.0f;  // optimizer slots
  }

  float* FindOrAlloc(Stripe* s, int64_t id) {
    auto it = s->index.find(id);
    if (it != s->index.end()) return s->arena.data() + it->second;
    const size_t off = s->arena.size();
    s->arena.resize(off + row_width_);
    s->index.emplace(id, off);
    return s->arena.data() + off;
  }

  float* FindOrInit(Stripe* s, int64_t id) {
    auto it = s->index.find(id);
    if (it != s->index.end()) return s->arena.data() + it->second;
    const size_t off = s->arena.size();
    s->arena.resize(off + row_width_);
    s->index.emplace(id, off);
    float* row = s->arena.data() + off;
    InitRow(id, row);
    return row;
  }

  void ApplyUpdate(float* row, const float* grad, float scale) {
    if (optimizer_ == kAdagrad) {
      float* slot = row + dim_;
      for (int d = 0; d < dim_; ++d) {
        const float g = grad[d] * scale;
        slot[d] += g * g;
        row[d] -= lr_ * g / (std::sqrt(slot[d]) + eps_);
      }
    } else {  // SGD
      for (int d = 0; d < dim_; ++d) {
        row[d] -= lr_ * grad[d] * scale;
      }
    }
  }

  const int dim_;
  const float init_std_;
  const uint64_t seed_;
  const int optimizer_;
  const float lr_;
  const float eps_;
  // Snapshot barrier: mutators hold it shared, Export holds it exclusive so
  // a checkpoint save mid-training sees a consistent point-in-time table.
  // glibc's pthread rwlock is reader-preferring, so a bare unique_lock could
  // starve forever under continuous pull/push traffic — the export_gate_
  // mutex (held by the exporter, touched by every new reader) makes new
  // readers BLOCK behind a pending exporter (writer preference) without
  // busy-waiting.
  std::shared_mutex& SharedBarrier() {
    { std::lock_guard<std::mutex> gate(export_gate_); }
    return snapshot_mu_;
  }

  class ExclusiveBarrier {
   public:
    explicit ExclusiveBarrier(EmbeddingStore* s) : s_(s) {
      s_->export_gate_.lock();   // new readers block here
      s_->snapshot_mu_.lock();   // existing readers drain
    }
    ~ExclusiveBarrier() {
      s_->snapshot_mu_.unlock();
      s_->export_gate_.unlock();
    }

   private:
    EmbeddingStore* s_;
  };

  const int row_width_;
  std::shared_mutex snapshot_mu_;
  std::mutex export_gate_;
  Stripe stripes_[kNumStripes];
};

}  // namespace

extern "C" {

void* eds_create(int dim, float init_std, uint64_t seed, int optimizer,
                 float lr, float eps) {
  return new EmbeddingStore(dim, init_std, seed, optimizer, lr, eps);
}

void eds_destroy(void* h) { delete static_cast<EmbeddingStore*>(h); }

int eds_row_width(void* h) {
  return static_cast<EmbeddingStore*>(h)->row_width();
}

void eds_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  static_cast<EmbeddingStore*>(h)->Pull(ids, n, out);
}

void eds_push(void* h, const int64_t* ids, int64_t n, const float* grads,
              float scale) {
  static_cast<EmbeddingStore*>(h)->Push(ids, n, grads, scale);
}

int64_t eds_size(void* h) { return static_cast<EmbeddingStore*>(h)->Size(); }

int64_t eds_export(void* h, int64_t* ids_out, float* rows_out,
                   int64_t capacity) {
  return static_cast<EmbeddingStore*>(h)->Export(ids_out, rows_out, capacity);
}

int64_t eds_export_snapshot(void* h, int64_t* ids_out, float* rows_out,
                            int64_t capacity, int64_t* size_out) {
  return static_cast<EmbeddingStore*>(h)->ExportSnapshot(ids_out, rows_out,
                                                         capacity, size_out);
}

void eds_import(void* h, const int64_t* ids, const float* rows, int64_t n) {
  static_cast<EmbeddingStore*>(h)->Import(ids, rows, n);
}

}  // extern "C"
