"""``python -m easydl_tpu.controller`` — run the elastic operator.

Two CR sources select where ElasticJob / JobResource documents come from:

- ``--watch-dir DIR`` (standalone): watch a directory of YAML documents —
  drop or update files to drive the job. Useful without a cluster.
- ``--cr-source k8s`` (in-cluster): LIST/WATCH the CRs on the Kubernetes
  API server (easydl_tpu/controller/kube_cr_source.py) — the reference's
  deployment shape (docs/design/elastic-training-operator.md:16-18,53-55),
  where ``kubectl apply`` of an ElasticJob is the only user action.

Either way the same reconcile loop runs against the selected pod backend:
``--pod-api memory`` logs decisions against the in-memory fake; ``k8s``
drives real cluster pods over the REST API.
"""

from __future__ import annotations

import argparse
import os
import time

import yaml

from easydl_tpu.api.job_spec import JOB_KIND, JobSpec
from easydl_tpu.api.resource_plan import PLAN_KIND, ResourcePlan
from easydl_tpu.controller import CrStore, ElasticJobController, InMemoryPodApi
from easydl_tpu.controller.operator import StalePlanError
from easydl_tpu.utils.logging import get_logger

log = get_logger("controller", "main")


def ingest(store: CrStore, path: str, seen: dict, pending: set) -> None:
    for fname in sorted(os.listdir(path)):
        if not fname.endswith((".yaml", ".yml")):
            continue
        full = os.path.join(path, fname)
        # One bad file (syntax error, deleted mid-scan) must not take the
        # operator down with it — log and move to the next file. A file is
        # marked seen only after every document lands, so transient failures
        # (a plan whose job file sorts after it, a momentary read error) are
        # retried on the next scan instead of being dropped forever.
        try:
            mtime = os.path.getmtime(full)
            if seen.get(full) == mtime:
                continue
            with open(full) as f:
                docs = [d for d in yaml.safe_load_all(f) if isinstance(d, dict)]
        except (OSError, yaml.YAMLError) as e:
            log.error("unreadable manifest %s: %s", fname, e)
            continue
        retry = False
        for doc in docs:
            try:
                if doc.get("kind") == JOB_KIND:
                    job = JobSpec.from_crd(doc)
                    if store.job(job.name) is None:
                        store.submit_job(job)
                        log.info("submitted job %s from %s", job.name, fname)
                elif doc.get("kind") == PLAN_KIND:
                    plan = ResourcePlan.from_crd(doc)
                    try:
                        store.apply_plan(plan)
                        log.info("applied plan v%d for %s from %s",
                                 plan.version, plan.job_name, fname)
                        pending.discard(full)
                    except StalePlanError:
                        pass  # already applied: file unchanged since
                    except KeyError:
                        # Job not ingested yet (or misspelled selector) —
                        # retry next scan, but say so once per file.
                        retry = True
                        if full not in pending:
                            pending.add(full)
                            log.warning(
                                "plan in %s targets unknown job %r; will "
                                "retry until the job appears",
                                fname, plan.job_name,
                            )
            except Exception as e:
                log.error("bad document in %s: %s", fname, e)
        if not retry:
            seen[full] = mtime


def main() -> None:
    ap = argparse.ArgumentParser(description="easydl_tpu elastic operator")
    ap.add_argument("--cr-source", choices=["dir", "k8s"], default="dir",
                    help="'dir' ingests CR YAMLs from --watch-dir; 'k8s' "
                         "LIST/WATCHes them on the API server")
    ap.add_argument("--watch-dir", default="",
                    help="directory of ElasticJob/JobResource YAMLs "
                         "(required with --cr-source dir)")
    ap.add_argument("--pod-api", choices=["memory", "k8s"], default="memory",
                    help="'k8s' reconciles real cluster pods over the k8s "
                         "REST API (in-cluster auth, or --kube-url)")
    ap.add_argument("--kube-url", default="",
                    help="k8s API server base URL (empty = in-cluster "
                         "service-account config)")
    ap.add_argument("--namespace", default="",
                    help="pod namespace (default: SA namespace or 'default')")
    ap.add_argument("--pod-workdir", default="",
                    help="in-container shared-workdir mount path substituted "
                         "into {workdir} command tokens (k8s pod api; "
                         "default /workdir)")
    ap.add_argument("--workdir-volume", default="",
                    help="JSON k8s volume source mounted at the pod workdir, "
                         'e.g. \'{"persistentVolumeClaim": {"claimName": '
                         '"train-shared"}}\'')
    ap.add_argument("--resync-s", type=float, default=2.0)
    args = ap.parse_args()
    if args.cr_source == "dir" and not args.watch_dir:
        ap.error("--watch-dir is required with --cr-source dir")

    store = CrStore()
    kube_client = None
    if args.pod_api == "k8s" or args.cr_source == "k8s":
        from easydl_tpu.controller.kube_http import KubeClient

        kube_client = KubeClient(base_url=args.kube_url,
                                 namespace=args.namespace)
    if args.pod_api == "k8s":
        import json

        from easydl_tpu.controller.kube_pod_api import (
            DEFAULT_WORKDIR,
            KubePodApi,
        )

        pod_api = KubePodApi(
            client=kube_client,
            workdir=args.pod_workdir or DEFAULT_WORKDIR,
            workdir_volume=(json.loads(args.workdir_volume)
                            if args.workdir_volume else None),
        )
    else:
        pod_api = InMemoryPodApi()
    ctl = ElasticJobController(store, pod_api)
    # Standalone mode: publish the controller's metrics address under the
    # watch dir (the operator-known location; ingest skips non-YAML
    # entries). In-cluster there is no shared dir — pin the port with
    # EASYDL_METRICS_PORT_CONTROLLER instead (docs/operations.md §4).
    ctl.start(resync_s=args.resync_s,
              obs_workdir=args.watch_dir or None)
    cr_source = None
    if args.cr_source == "k8s":
        from easydl_tpu.controller.kube_cr_source import (
            KubeCrSource,
            make_status_writer,
        )

        store.add_status_sink(make_status_writer(kube_client))
        cr_source = KubeCrSource(store, kube_client).start()
        log.info("operator watching CRs on %s (pod api: %s)",
                 kube_client.base_url, args.pod_api)
    else:
        log.info("operator watching %s (pod api: %s)",
                 args.watch_dir, args.pod_api)
    seen: dict = {}
    pending: set = set()
    try:
        while True:
            if args.cr_source == "dir":
                ingest(store, args.watch_dir, seen, pending)
            if args.pod_api == "memory":
                pod_api.tick()  # the fake cluster needs a clock
            time.sleep(min(args.resync_s, 1.0))
    except KeyboardInterrupt:
        pass
    finally:
        if cr_source is not None:
            cr_source.stop()
        ctl.stop()
        # Drain the async status-sink queue before exiting: the final
        # /status PATCH (often the terminal-phase latch) must not die with
        # the daemon dispatch thread.
        store.flush_status()
        store.close()


if __name__ == "__main__":
    main()
