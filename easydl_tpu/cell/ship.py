"""The cross-cell WAL shipper: asynchronous replication with a measured RPO.

One :class:`CellShipper` instance runs INSIDE the primary cell (or on a
box that can read its workdir) and pumps everything a rescue would read
into a standby cell's workdir, laid out identically, so promotion
(:mod:`easydl_tpu.cell.promote`) is nothing more than booting PS pods on
the standby workdir through the EXISTING rescue path:

- **WAL segments** (``ps-wal/shard-<i>/epoch-<e>/seg-*.wal``): tailed
  with the spool cursor discipline (loop/spool.py ``read_segment(start=)``
  — a poll pays for new bytes only), every record CRC-verified, then
  re-framed byte-identically into the standby's matching segment file.
  Because rotation closes a segment before its successor is written
  (SegmentWriter rotates BEFORE the write), a segment with a live
  successor is immutable — the shipper only marks a segment *complete*
  (and advances its cursor past it) once a successor exists and the read
  reached a clean EOF. Ship order is strictly (epoch, segment, offset),
  so the standby's copy is always a byte-prefix of the primary's stream:
  replay on the standby applies a *prefix of the acked pushes*, never a
  subset with holes.
- **Snapshots** (``ps-ckpt/step_*``): only cluster-complete steps (all
  ``.done-*`` markers present), staged into a temp dir and renamed into
  place atomically — a half-shipped snapshot is invisible to
  ``saved_steps`` on the standby.
- **Epoch counters** (``ps/epoch-shard-<i>.json``): raised-to-floor on
  the standby (never lowered), so promotion's bump yields an epoch
  strictly above anything the primary ever served at — the fencing
  token.
- **Rollout versions** (``models/v_*`` + commit markers, loop/publish.py)
  and **serve discovery** (``serve/*.json``): the standby fleet's serving
  bootstrap.

Durability of the ship position: the destination files themselves are
append-only and frame-aligned, and the cursor marker
(``cell-ship/ship-cursor.json``) is written atomically after every pass.
A crash between the two is healed on the next pass by re-reading the
destination tail (``read_segment``) and skipping already-landed frames —
re-shipping never duplicates a record on the standby (a duplicate would
replay as a double-apply: divergence).

Loud degradation (never silent): a cursor whose segment was retired
underneath it (``easydl_cell_ship_gaps_total``) or truncated below the
shipped offset (``easydl_cell_ship_truncations_total``) is counted and
logged at ERROR — the bytes are only safe if a shipped snapshot covers
them, which the promotion decision (:mod:`easydl_tpu.cell.policy`)
checks explicitly. The current unshipped byte count is exported as the
``easydl_cell_replication_lag`` gauge: the measured RPO.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional, Tuple

from easydl_tpu.loop.spool import frame, list_segments, read_segment
from easydl_tpu.ps import registry as ps_registry
from easydl_tpu.ps import wal as ps_wal
from easydl_tpu.utils.env import knob_float, knob_int
from easydl_tpu.utils.logging import get_logger

log = get_logger("cell", "ship")

ENV_SHIP_INTERVAL_S = "EASYDL_CELL_SHIP_INTERVAL_S"
ENV_LAG_SLO_BYTES = "EASYDL_CELL_LAG_SLO_BYTES"

DEFAULT_SHIP_INTERVAL_S = 0.5
DEFAULT_LAG_SLO_BYTES = 4 << 20

SHIP_DIR = "cell-ship"
CURSOR_FILE = "ship-cursor.json"
#: written by promote.write_promoted_marker — a promoted standby is a
#: PRIMARY now; shipping into it would corrupt the new lineage.
PROMOTED_MARKER = "PROMOTED.json"


class ShipFenced(RuntimeError):
    """The standby was promoted — it is a primary now. Shipping into it
    would append a dead cell's bytes under the new lineage's feet, so
    every pass against a promoted standby fails loudly."""


def _metrics():
    """Lazy metric families (import-cycle-free, registered once)."""
    global _METRICS
    if _METRICS is None:
        from easydl_tpu.obs.registry import get_registry

        reg = get_registry()
        _METRICS = {
            "segments": reg.counter(
                "easydl_cell_shipped_segments_total",
                "WAL segments fully shipped to the standby cell",
                labelnames=("cell",)),
            "bytes": reg.counter(
                "easydl_cell_shipped_bytes_total",
                "WAL payload bytes shipped to the standby cell",
                labelnames=("cell",)),
            "records": reg.counter(
                "easydl_cell_shipped_records_total",
                "WAL records shipped to the standby cell",
                labelnames=("cell",)),
            "snapshots": reg.counter(
                "easydl_cell_shipped_snapshots_total",
                "complete ps-ckpt steps shipped to the standby cell",
                labelnames=("cell",)),
            "versions": reg.counter(
                "easydl_cell_shipped_versions_total",
                "committed rollout versions shipped to the standby cell",
                labelnames=("cell",)),
            "torn": reg.counter(
                "easydl_cell_ship_torn_segments_total",
                "dead-writer torn tails truncated while shipping",
                labelnames=("cell",)),
            "truncations": reg.counter(
                "easydl_cell_ship_truncations_total",
                "source segments found truncated below the ship cursor",
                labelnames=("cell",)),
            "gaps": reg.counter(
                "easydl_cell_ship_gaps_total",
                "ship-cursor positions retired out from under the shipper",
                labelnames=("cell",)),
            "errors": reg.counter(
                "easydl_cell_ship_errors_total",
                "ship passes that raised",
                labelnames=("cell",)),
            "lag": reg.gauge(
                "easydl_cell_replication_lag",
                "bytes of acked WAL not yet shipped to the standby "
                "cell (the measured RPO bound)",
                labelnames=("cell",)),
        }
    return _METRICS


_METRICS = None


@dataclass
class ShipStats:
    """One pass's (or the lifetime's) replication accounting."""

    segments_completed: int = 0
    bytes_shipped: int = 0
    records_shipped: int = 0
    snapshots_shipped: int = 0
    versions_shipped: int = 0
    serve_files_shipped: int = 0
    epochs_floored: int = 0
    torn_skipped: int = 0
    truncations: int = 0
    gaps: int = 0
    errors: int = 0
    lag_bytes: int = 0

    def merge(self, other: "ShipStats") -> None:
        for f in fields(self):
            if f.name == "lag_bytes":  # a level, not a count
                self.lag_bytes = other.lag_bytes
            else:
                setattr(self, f.name,
                        getattr(self, f.name) + getattr(other, f.name))

    def to_dict(self) -> Dict[str, int]:
        return {f.name: int(getattr(self, f.name)) for f in fields(self)}


@dataclass
class _Cursor:
    """Durable per-shard ship position: everything before ``(epoch,
    segment, offset)`` in (epoch, segment-name, byte) order is on the
    standby. ``dst_offset`` is the matching byte count in the standby's
    copy of ``segment`` — equal to ``offset`` minus the source start of
    what we shipped, tracked separately so a source truncation anomaly
    (offsets diverge) stays recoverable."""

    epoch: int = 0
    segment: str = ""
    offset: int = 0
    dst_offset: int = 0
    records: int = 0

    def to_dict(self) -> Dict[str, object]:
        return {"epoch": int(self.epoch), "segment": self.segment,
                "offset": int(self.offset),
                "dst_offset": int(self.dst_offset),
                "records": int(self.records)}

    @staticmethod
    def from_dict(doc) -> "_Cursor":
        doc = dict(doc or {})
        return _Cursor(
            epoch=int(doc.get("epoch", 0)),
            segment=str(doc.get("segment", "")),
            offset=int(doc.get("offset", 0)),
            dst_offset=int(doc.get("dst_offset", 0)),
            records=int(doc.get("records", 0)))


def _fsync_write(path: str, data: bytes) -> None:
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_copy(src: str, dst: str) -> None:
    tmp = dst + ".ship-tmp"
    shutil.copyfile(src, tmp)
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    os.replace(tmp, dst)


class CellShipper:
    """Pump one primary workdir's durable state into a standby workdir.

    Single-threaded per instance: :meth:`ship_once` runs one full pass;
    :meth:`start`/:meth:`stop` wrap it in a background cadence loop
    (``EASYDL_CELL_SHIP_INTERVAL_S``). NOT safe to run two shippers into
    the same standby."""

    def __init__(self, primary: str, standby: str, num_shards: int,
                 cell: str = "standby", models_dir: str = "models",
                 interval_s: Optional[float] = None):
        self.primary = primary
        self.standby = standby
        self.num_shards = int(num_shards)
        self.cell = cell
        self.models_dir = models_dir
        self.interval_s = float(
            knob_float(ENV_SHIP_INTERVAL_S, DEFAULT_SHIP_INTERVAL_S)
            if interval_s is None else interval_s)
        self.total = ShipStats()
        self.last_pass_monotonic: float = float("-inf")
        os.makedirs(os.path.join(standby, SHIP_DIR), exist_ok=True)
        self._cursors: Dict[int, _Cursor] = self._load_cursors()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()

    # ------------------------------------------------------------- cursor io
    def _cursor_path(self) -> str:
        return os.path.join(self.standby, SHIP_DIR, CURSOR_FILE)

    def _load_cursors(self) -> Dict[int, _Cursor]:
        try:
            with open(self._cursor_path()) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        return {int(s): _Cursor.from_dict(c)
                for s, c in dict(doc.get("shards", {})).items()}

    def _save_cursors(self) -> None:
        path = self._cursor_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"shards": {str(s): c.to_dict()
                                  for s, c in self._cursors.items()}}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # ------------------------------------------------------------- wal ship
    def _wal_positions(self, shard: int
                       ) -> List[Tuple[int, str, str, List[str]]]:
        """Epoch-ordered ``(epoch, epoch_dirname, path, segments)`` of the
        shard's source WAL."""
        root = os.path.join(self.primary, "ps-wal", f"shard-{shard}")
        out = []
        for epoch, d in ps_wal.epoch_dirs(root):
            out.append((epoch, os.path.basename(d), d,
                        list_segments(d, ".wal")))
        return out

    def _ship_segment(self, shard: int, epoch: int, epoch_name: str,
                      src_path: str, cur: _Cursor, stats: ShipStats) -> None:
        """Tail one source segment from the cursor and append the verified
        frames to the standby's copy, healing any crash-torn destination
        tail first."""
        name = os.path.basename(src_path)
        dst_dir = os.path.join(self.standby, "ps-wal", f"shard-{shard}",
                               epoch_name)
        os.makedirs(dst_dir, exist_ok=True)
        dst_path = os.path.join(dst_dir, name)
        if cur.segment != name or cur.epoch != epoch:
            cur.epoch, cur.segment = epoch, name
            cur.offset = cur.dst_offset = 0
        try:
            src_size = os.path.getsize(src_path)
        except OSError:
            return  # raced a retirement; the caller's gap check judges it
        if src_size < cur.offset:
            # Source shrank below what we shipped. The only sanctioned
            # writer-side shrink is SegmentWriter.rollback of a frame
            # whose apply FAILED (never acked) — the standby now holds a
            # frame the primary disowned. Harmless to replay (the push
            # was never acked either way) but never silent.
            stats.truncations += 1
            _metrics()["truncations"].inc(cell=self.cell)
            log.error(
                "cell ship: source segment %s truncated to %d below ship "
                "cursor %d (rolled-back frame already shipped); "
                "re-syncing cursor", src_path, src_size, cur.offset)
            cur.offset = src_size
            return
        # Heal a crash between dest-append and cursor-save: whatever
        # clean frames sit past dst_offset in the destination are frames
        # we already shipped from cur.offset on — skip them, and drop a
        # torn destination tail (partial writev) before appending more.
        try:
            dst_size = os.path.getsize(dst_path)
        except OSError:
            dst_size = 0
        if dst_size > cur.dst_offset:
            landed, dst_clean_end, _clean = read_segment(
                dst_path, start=cur.dst_offset)
            if dst_clean_end < dst_size:
                with open(dst_path, "rb+") as f:
                    f.truncate(dst_clean_end)
            for p in landed:
                cur.offset += len(frame(p))
                cur.dst_offset += len(frame(p))
                cur.records += 1
        elif dst_size < cur.dst_offset:
            # The standby's copy lost bytes (manual tampering, fs loss):
            # re-ship the difference from the source if it still has it.
            log.error("cell ship: standby copy %s shorter (%d) than the "
                      "cursor (%d); re-shipping the tail", dst_path,
                      dst_size, cur.dst_offset)
            cur.offset = max(0, cur.offset - (cur.dst_offset - dst_size))
            cur.dst_offset = dst_size
        payloads, consumed, clean = read_segment(src_path, start=cur.offset)
        if payloads:
            buf = b"".join(frame(p) for p in payloads)
            _fsync_write(dst_path, buf)
            cur.offset = consumed
            cur.dst_offset += len(buf)
            cur.records += len(payloads)
            stats.bytes_shipped += len(buf)
            stats.records_shipped += len(payloads)
            _metrics()["bytes"].inc(len(buf), cell=self.cell)
            _metrics()["records"].inc(len(payloads), cell=self.cell)
        if not clean:
            # Torn/corrupt frame. In the NEWEST segment of the NEWEST
            # epoch this is a live writer mid-append — pending, not
            # damage. Anywhere else the writer is dead or rotated away:
            # count it; the caller advances past the segment.
            stats.torn_skipped += 1

    def _ship_wal_shard(self, shard: int, stats: ShipStats) -> int:
        """One shard's WAL pass; returns this shard's remaining lag in
        bytes (source bytes past the cursor after the pass)."""
        cur = self._cursors.setdefault(shard, _Cursor())
        positions = self._wal_positions(shard)
        if not positions:
            return 0
        # Gap check: the cursor's position must still exist, unless the
        # cursor is virgin. A retired epoch dir or segment under the
        # cursor means bytes we never shipped are gone from the source —
        # recoverable ONLY through a shipped snapshot, and always loud.
        if cur.segment:
            by_epoch = {e: segs for e, _n, _d, segs in positions}
            live = cur.epoch in by_epoch and (
                cur.segment in by_epoch[cur.epoch])
            behind = any(
                e > cur.epoch or (e == cur.epoch and any(
                    s > cur.segment for s in segs))
                for e, segs in by_epoch.items())
            if not live and behind:
                stats.gaps += 1
                _metrics()["gaps"].inc(cell=self.cell)
                nxt_e, nxt_name, _d, nxt_segs = next(
                    (p for p in positions if p[0] >= cur.epoch and p[3]),
                    positions[-1])
                log.error(
                    "cell ship: shard %d cursor %s/epoch-%d retired out "
                    "from under the shipper; resyncing to epoch %d "
                    "(acked bytes in the gap are only safe if a shipped "
                    "snapshot covers them)", shard, cur.segment,
                    cur.epoch, nxt_e)
                self._cursors[shard] = cur = _Cursor(epoch=nxt_e)
        torn_before = stats.torn_skipped
        for idx, (epoch, epoch_name, d, segs) in enumerate(positions):
            if epoch < cur.epoch:
                continue
            newest_epoch = idx == len(positions) - 1
            for s_idx, name in enumerate(segs):
                if epoch == cur.epoch and cur.segment and \
                        name < cur.segment:
                    continue
                self._ship_segment(shard, epoch, epoch_name,
                                   os.path.join(d, name), cur, stats)
                closed = (s_idx < len(segs) - 1) or not newest_epoch
                if closed:
                    # Rotation wrote a successor, so this segment is
                    # immutable — fully shipped, advance past it. (A
                    # torn tail here is a dead writer's: already counted
                    # by _ship_segment, safe to move on.)
                    if stats.torn_skipped > torn_before:
                        _metrics()["torn"].inc(
                            stats.torn_skipped - torn_before,
                            cell=self.cell)
                        torn_before = stats.torn_skipped
                    stats.segments_completed += 1
                    _metrics()["segments"].inc(cell=self.cell)
                    nxt = (segs[s_idx + 1] if s_idx < len(segs) - 1
                           else "")
                    cur.epoch, cur.segment = epoch, nxt
                    cur.offset = cur.dst_offset = 0
                    if not nxt:
                        cur.epoch = epoch + 1  # move into the next epoch
                else:
                    # Open segment: the cursor rests inside it; a torn
                    # tail is pending, not damage.
                    stats.torn_skipped = torn_before
        # Lag: source bytes at/past the cursor, from a fresh listing
        # (bytes appended during this pass count — that is the RPO).
        lag = 0
        for epoch, _n, d, segs in self._wal_positions(shard):
            if epoch < cur.epoch:
                continue
            for name in segs:
                if epoch == cur.epoch and cur.segment and \
                        name < cur.segment:
                    continue
                try:
                    size = os.path.getsize(os.path.join(d, name))
                except OSError:
                    continue
                if epoch == cur.epoch and name == cur.segment:
                    lag += max(0, size - cur.offset)
                else:
                    lag += size
        return lag

    # -------------------------------------------------------- control plane
    def _ship_snapshots(self, stats: ShipStats) -> None:
        from easydl_tpu.ps.server import PsShard

        src = os.path.join(self.primary, "ps-ckpt")
        dst = os.path.join(self.standby, "ps-ckpt")
        src_steps = PsShard.saved_steps(src)
        if not src_steps:
            return
        os.makedirs(dst, exist_ok=True)
        have = set(PsShard.saved_steps(dst))
        for step in src_steps:
            if step in have:
                continue
            sdir = os.path.join(src, f"step_{step:010d}")
            tmp = os.path.join(dst, f".ship-tmp-step_{step:010d}")
            final = os.path.join(dst, f"step_{step:010d}")
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            try:
                names = sorted(os.listdir(sdir))
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
                continue  # snapshot retired mid-pass; next pass re-lists
            # Completeness markers last, inside the staging dir; the
            # rename is what makes the whole step appear atomically.
            for name in [n for n in names if not n.startswith(".done-")] \
                    + [n for n in names if n.startswith(".done-")]:
                _atomic_copy(os.path.join(sdir, name),
                             os.path.join(tmp, name))
            shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
            stats.snapshots_shipped += 1
            _metrics()["snapshots"].inc(cell=self.cell)

    def _ship_epochs(self, stats: ShipStats) -> None:
        from easydl_tpu.cell.promote import ensure_epoch_floor

        for shard in range(self.num_shards):
            src_epoch = ps_registry.shard_epoch(self.primary, shard)
            if src_epoch <= 0:
                continue
            if ensure_epoch_floor(self.standby, shard, src_epoch):
                stats.epochs_floored += 1
        routing = os.path.join(self.primary, ps_registry.REG_DIR,
                               ps_registry.ROUTING_FILE)
        if os.path.exists(routing):
            dst_dir = os.path.join(self.standby, ps_registry.REG_DIR)
            os.makedirs(dst_dir, exist_ok=True)
            _atomic_copy(routing,
                         os.path.join(dst_dir, ps_registry.ROUTING_FILE))

    def _ship_rollout(self, stats: ShipStats) -> None:
        from easydl_tpu.loop import publish

        src = os.path.join(self.primary, self.models_dir)
        if not os.path.isdir(src):
            return
        dst = os.path.join(self.standby, self.models_dir)
        os.makedirs(dst, exist_ok=True)
        have = set(publish.list_versions(dst))
        for v in publish.list_versions(src):  # committed versions only
            if v in have:
                continue
            sdir = os.path.join(src, f"v_{v:08d}")
            tmp = os.path.join(dst, f".ship-tmp-v_{v:08d}")
            final = os.path.join(dst, f"v_{v:08d}")
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            try:
                names = sorted(os.listdir(sdir))
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
                continue
            # COMMITTED strictly last within the staging dir (publish.py's
            # own marker-last discipline), then one atomic rename.
            for name in [n for n in names if n != "COMMITTED"] \
                    + [n for n in names if n == "COMMITTED"]:
                _atomic_copy(os.path.join(sdir, name),
                             os.path.join(tmp, name))
            shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)
            stats.versions_shipped += 1
            _metrics()["versions"].inc(cell=self.cell)
        rollback = os.path.join(src, "rollback.json")
        if os.path.exists(rollback):
            _atomic_copy(rollback, os.path.join(dst, "rollback.json"))

    def _ship_serve_discovery(self, stats: ShipStats) -> None:
        src = os.path.join(self.primary, "serve")
        if not os.path.isdir(src):
            return
        dst = os.path.join(self.standby, "serve")
        os.makedirs(dst, exist_ok=True)
        for name in sorted(os.listdir(src)):
            if not name.endswith(".json"):
                continue
            try:
                _atomic_copy(os.path.join(src, name),
                             os.path.join(dst, name))
                stats.serve_files_shipped += 1
            except OSError:
                continue  # replica stopped mid-copy; next pass re-lists

    # ------------------------------------------------------------------ api
    def ship_once(self) -> ShipStats:
        """One full replication pass; returns the pass's stats (and folds
        them into :attr:`total`). Raises :class:`ShipFenced` against a
        promoted standby."""
        with self._mu:
            if os.path.exists(os.path.join(self.standby, SHIP_DIR,
                                           PROMOTED_MARKER)):
                raise ShipFenced(
                    f"standby {self.standby} was promoted; refusing to "
                    "ship a dead primary's bytes into a live lineage")
            stats = ShipStats()
            try:
                lag = 0
                for shard in range(self.num_shards):
                    lag += self._ship_wal_shard(shard, stats)
                self._save_cursors()
                self._ship_snapshots(stats)
                self._ship_epochs(stats)
                self._ship_rollout(stats)
                self._ship_serve_discovery(stats)
                stats.lag_bytes = lag
                _metrics()["lag"].set(lag, cell=self.cell)
            except ShipFenced:
                raise
            except Exception:
                stats.errors += 1
                _metrics()["errors"].inc(cell=self.cell)
                raise
            finally:
                self.total.merge(stats)
                self.last_pass_monotonic = time.monotonic()
            return stats

    def lag_bytes(self) -> int:
        """Last measured replication lag (bytes acked-but-unshipped)."""
        return int(self.total.lag_bytes)

    def start(self) -> "CellShipper":
        """Run :meth:`ship_once` on the configured cadence until
        :meth:`stop` (or the standby is promoted)."""
        if self._thread is not None:
            return self

        def run() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.ship_once()
                except ShipFenced:
                    log.info("cell ship loop: standby promoted; stopping")
                    return
                except Exception as e:
                    log.error("cell ship pass failed: %s", e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="cell-ship")
        self._thread.start()
        return self

    def stop(self, drain: bool = False) -> None:
        """Stop the cadence loop. With ``drain`` a final pass runs after
        the loop exits (a clean handover wants lag 0; a DISASTER drill
        must NOT drain — the unshipped tail is the measured RPO)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, 4 * self.interval_s))
            self._thread = None
        if drain:
            self.ship_once()
