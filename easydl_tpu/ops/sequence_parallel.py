"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Long sequences shard over the mesh's ``sp`` axis (easydl_tpu/core/mesh.py
puts ``sp`` innermost with ``tp`` so its collectives ride nearest-neighbour
ICI). Two attention strategies, both pure JAX inside ``shard_map``:

- :func:`ring_attention` — KV blocks rotate around the ring via ``ppermute``
  while each device folds them into an online softmax. The per-device score
  matrix is [s_loc, s_loc] (S²/n² memory), and each ring step is wrapped in
  ``jax.checkpoint`` so the backward *re-permutes* KV instead of storing all
  n rotated copies — the classic two-pass ring backward, expressed as remat
  + XLA autodiff rather than a hand-written VJP.
- :func:`ulysses_attention` — two ``all_to_all``\\ s re-shard [b, s/n, H, d]
  → [b, S, H/n, d] so each device runs *full-sequence* attention over a head
  slice (the Pallas flash kernel applies locally), then shards back. Cheaper
  collectives than the ring when heads ≥ ring size; requires H % n == 0.

Both see sequence shards as contiguous blocks in rank order — exactly what
``shard_map`` with ``P(None, "sp", None, None)`` provides.
:func:`make_sp_attention` builds that wrapper over a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from easydl_tpu.ops._compat import shard_map

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_attend(q, k_blk, v_blk, q_start, k_start, *, causal: bool, scale: float):
    """One (q-shard × kv-block) partial: returns (m, l, acc) statistics.

    q: [b, sq, h, d]; k_blk/v_blk: [b, sk, h, d]; positions are global
    offsets of the shards (k_start is traced — it changes per ring step).
    """
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_blk,
        preferred_element_type=jnp.float32,
    ) * scale
    sq, sk = q.shape[1], k_blk.shape[1]
    if causal:
        q_pos = q_start + jnp.arange(sq)
        k_pos = k_start + jnp.arange(sk)
        allowed = q_pos[:, None] >= k_pos[None, :]  # [sq, sk]
        logits = jnp.where(allowed[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)  # [b,h,sq]
    p = jnp.exp(logits - m[..., None])
    if causal:
        # Fully-masked rows have m == NEG_INF and exp(0) == 1 artifacts;
        # zero them through the same mask.
        p = jnp.where(allowed[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [b,h,sq]
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return m, l, acc


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Blockwise ring attention over sequence shards (call inside shard_map).

    q/k/v: [batch, s_local, heads, head_dim], the ``axis_name`` shard of the
    global sequence in rank order. Returns the local output shard.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    q32 = q.astype(jnp.float32)

    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)

    # n is a static mesh-axis size: unroll. Each step re-derives its KV block
    # by rotating the ORIGINAL shard s hops (single ppermute), inside a
    # checkpoint region so the backward re-communicates instead of saving
    # every rotated copy.
    @functools.partial(jax.checkpoint, static_argnums=(3,))
    def step(q32, kv, carry, s):
        m, l, acc = carry
        perm = [(i, (i + s) % n) for i in range(n)]
        k_s = lax.ppermute(kv[0], axis_name, perm)
        v_s = lax.ppermute(kv[1], axis_name, perm)
        src = (idx - s) % n  # whose sequence block arrived
        m_b, l_b, acc_b = _block_attend(
            q32, k_s, v_s, idx * s_loc, src * s_loc, causal=causal, scale=scale
        )
        m_new = jnp.maximum(m, m_b)
        c_old = jnp.exp(m - m_new)
        c_new = jnp.exp(m_b - m_new)
        l_new = l * c_old + l_b * c_new
        acc_new = acc * c_old[..., None] + acc_b * c_new[..., None]
        return m_new, l_new, acc_new

    for s in range(n):
        m, l, acc = step(q32, (k, v), (m, l, acc), s)

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [b,h,sq,d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Head-parallel attention via all-to-all (call inside shard_map).

    Re-shards [b, s/n, H, d] → [b, S, H/n, d], runs full-sequence attention
    on the local head group (flash kernel on TPU), and shards back.
    """
    from easydl_tpu.ops.attention import multihead_attention

    n = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"heads={h} not divisible by sp={n}")

    def seq_gather(x):  # [b, s/n, H, d] -> [b, S, H/n, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def seq_scatter(x):  # [b, S, H/n, d] -> [b, s/n, H, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    out = multihead_attention(
        seq_gather(q), seq_gather(k), seq_gather(v),
        causal=causal, scale=scale, impl=impl,
    )
    return seq_scatter(out)


def make_sp_attention(
    mesh: Mesh,
    kind: str = "ring",
    axis: str = "sp",
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    impl: str = "auto",
):
    """Wrap a sequence-parallel attention as a ``(q, k, v, causal=...)``
    function over GLOBAL [b,S,h,d] arrays.

    Under jit/GSPMD it runs the ring / Ulysses program via shard_map over
    ``mesh[axis]``; batch stays sharded over the dp axes, sequence over
    ``axis``. The ``causal`` argument here is only the *default* — a model
    passes its own flag per call (TransformerConfig.causal), so a
    bidirectional model can never silently inherit causal masking.
    """
    if kind not in ("ring", "ulysses"):
        raise ValueError(f"unknown sp attention kind {kind!r}")

    spec = P(("dp", "fsdp"), axis, None, None)
    n_batch = mesh.shape["dp"] * mesh.shape["fsdp"]
    n_sp = mesh.shape[axis]
    sharded_cache: dict = {}

    def sharded_for(is_causal: bool):
        if is_causal not in sharded_cache:
            if kind == "ring":
                inner = functools.partial(
                    ring_attention, axis_name=axis, causal=is_causal, scale=scale
                )
            else:
                inner = functools.partial(
                    ulysses_attention, axis_name=axis, causal=is_causal,
                    scale=scale, impl=impl,
                )
            sharded_cache[is_causal] = shard_map(
                lambda q, k, v: inner(q, k, v),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
        return sharded_cache[is_causal]

    default_causal = causal

    def dispatch(q, k, v, causal: Optional[bool] = None):
        is_causal = default_causal if causal is None else causal
        if q.shape[0] % n_batch or q.shape[1] % n_sp:
            # The batch-1 trace inside model.init is the one legitimate
            # non-tiling shape (parameter shapes don't depend on activation
            # values) — run it locally. Any other mismatch is a user error;
            # falling back silently would materialise full S×S attention,
            # the exact blow-up SP exists to avoid.
            if q.shape[0] == 1:
                from easydl_tpu.ops.attention import multihead_attention

                return multihead_attention(
                    q, k, v, causal=is_causal, scale=scale, impl="reference"
                )
            raise ValueError(
                f"sp attention: shapes batch={q.shape[0]}, seq={q.shape[1]} "
                f"don't tile over mesh (batch shards={n_batch}, {axis}={n_sp})"
            )
        return sharded_for(is_causal)(q, k, v)

    return dispatch
