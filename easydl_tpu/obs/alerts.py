"""The live alerting half: evaluator, alert ledger, and drill recorder.

:mod:`easydl_tpu.brain.alert_policy` decides; this module feeds it. An
:class:`AlertEvaluator` owns one :class:`~AlertPolicy` over the loaded
SLO specs and, each tick, folds a fleet metric snapshot into a bounded
history window, runs the pure decision, and

- appends the FULL (inputs, verdict) record to a spool-framed JSONL
  ledger (``loop/spool.py`` framing: CRC-checked, torn-tail-safe — the
  same machinery every other durable stream in the repo rides), which is
  what :func:`replay_ledger` re-derives byte-identically offline;
- exports ``easydl_alert_active{slo,severity}`` so the alert state is
  itself a scrape-able series;
- serves a ``/healthz`` rollup (:meth:`AlertEvaluator.healthz`) naming
  each firing SLO and its runbook anchor — the thing a human reads
  first.

:class:`AlertRecorder` is the chaos harness' witness thread: during a
drill it snapshots the harness process' own registry plus every
subprocess exporter discovered under the drill workdir(s), feeds the
evaluator, and on stop returns the evidence document the
``detected_and_cleared`` invariant family judges — when the expected
alert fired (TTD), whether it cleared, what paged, and whether the
ledger replays byte-identically.

The recorder also acts as the scrape-side janitor: a discovery doc
whose scrape failed AND whose pid is provably dead is retired (the
mirror of ``exporter._sweep_stale``, which only runs when a NEW exporter
publishes into the same directory — after a whole-cell kill nothing
ever publishes into the dead primary's workdir, and without the janitor
the scrape-health alert could never clear). The failed scrape is always
COUNTED first — detection before cleanup — and a SIGSTOPped (alive)
target is never retired, so a zombie keeps failing scrapes until it
wakes, exactly the alert shape a partition should have.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from easydl_tpu.brain.alert_policy import (
    AlertPolicy, decision_bytes, parse_selector, replay_decision_log,
)
from easydl_tpu.loop import spool
from easydl_tpu.obs.exporter import OBS_DIR
from easydl_tpu.obs.registry import MetricsRegistry, get_registry
from easydl_tpu.utils.env import knob_float, knob_int

log = logging.getLogger("easydl.alerts")

#: ledger record kind byte (spool payloads lead with one)
ALERT_RECORD = 7

#: ledger segment filename suffix
LEDGER_SUFFIX = ".alerts"


def _relevant_families(specs: Sequence[Mapping[str, Any]]) -> frozenset:
    from easydl_tpu.obs.slo import referenced_series

    fams = set()
    for spec in specs:
        for sel in referenced_series(spec):
            fams.add(parse_selector(sel)[0])
    return frozenset(fams)


class AlertEvaluator:
    """Tick-driven: ``tick(samples, now)`` → the canonical decision.

    Owns the history window (trimmed to the longest spec window plus
    slack), the ledger writer, and the ``easydl_alert_active`` gauge.
    Thread-compatible, not thread-safe — one caller ticks it."""

    def __init__(self, specs: Sequence[Mapping[str, Any]],
                 ledger_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 segment_bytes: Optional[int] = None):
        self.policy = AlertPolicy(specs)
        self.runbooks = {str(s.get("name", "")): str(s.get("runbook", ""))
                         for s in specs}
        self._families = _relevant_families(specs)
        self._history: List[Dict[str, Any]] = []
        self._span_s = max(
            [float(dict(s.get("windows") or {}).get("long_s", 6.0))
             for s in specs] or [6.0]) + 2.0
        self._writer: Optional[spool.SegmentWriter] = None
        if ledger_dir:
            self._writer = spool.SegmentWriter(
                ledger_dir,
                int(segment_bytes
                    or knob_int("EASYDL_ALERT_LEDGER_SEGMENT_BYTES")),
                sync_s=0.2, suffix=LEDGER_SUFFIX)
        reg = registry or get_registry()
        self._gauge = reg.gauge(
            "easydl_alert_active",
            "1 while the SLO's multiwindow burn-rate alert is firing.",
            ("slo", "severity"))
        self.last: Dict[str, Any] = {}

    def tick(self, samples: Mapping[str, float], now: float
             ) -> Dict[str, Any]:
        restricted = {
            key: float(v) for key, v in samples.items()
            if key.partition("{")[0] in self._families}
        self._history.append({"t": round(float(now), 6), "s": restricted})
        lo = float(now) - self._span_s
        self._history = [h for h in self._history if h["t"] >= lo]
        decision = self.policy.evaluate(self._history, now)
        if self._writer is not None:
            record = self.policy.log[-1]
            try:
                self._writer.append(
                    bytes([ALERT_RECORD]) + json.dumps(
                        record, sort_keys=True,
                        separators=(",", ":")).encode())
            except spool.SpoolError as e:  # alerting outlives its ledger
                log.warning("alert ledger append failed: %s", e)
        for name, a in decision["alerts"].items():
            self._gauge.set(1.0 if a["active"] else 0.0,
                            slo=name, severity=a["severity"])
        self.last = decision
        return decision

    def healthz(self) -> Dict[str, Any]:
        """The /healthz rollup: every firing SLO with its severity and
        runbook anchor (what start_exporter's health_fn serves)."""
        alerts = dict(self.last.get("alerts") or {})
        firing = [n for n in sorted(alerts) if alerts[n]["active"]]
        return {
            "alerts_ok": not firing,
            "firing": [
                {"slo": n, "severity": alerts[n]["severity"],
                 "since": alerts[n]["since"],
                 "runbook": self.runbooks.get(n, "")}
                for n in firing],
            "pages": [n for n in firing
                      if alerts[n]["severity"] == "page"],
        }

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


def read_ledger(directory: str) -> List[Dict[str, Any]]:
    """Every decision record in the ledger, append order — the replay
    gate's input. Torn tails stop the read (spool semantics); a torn
    final record just shortens the log."""
    out: List[Dict[str, Any]] = []
    for name in spool.list_segments(directory, LEDGER_SUFFIX):
        payloads, _, _ = spool.read_segment(os.path.join(directory, name))
        for p in payloads:
            if spool.record_kind(p) != ALERT_RECORD:
                continue
            try:
                out.append(json.loads(p[1:].decode()))
            except ValueError:
                continue
    return out


def replay_ledger(directory: str) -> Dict[str, Any]:
    """Offline byte-replay of a persisted ledger — every drill verdict
    carries this result."""
    return replay_decision_log(read_ledger(directory))


def _is_zombie(pid: int) -> bool:
    """True iff ``pid`` is a zombie (Linux: state field of
    /proc/<pid>/stat, after the parenthesised comm which may itself
    contain spaces). Unreadable/absent procfs reads as not-a-zombie."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        return stat.rpartition(b")")[2].split()[0] == b"Z"
    except (OSError, IndexError):
        return False


class AlertRecorder:
    """Background witness for chaos drills: scrape + evaluate on a
    cadence, return the detection evidence on stop.

    ``scan_dirs`` may grow mid-drill (the cell drill's primary/standby
    subdirectories appear after start); each tick re-resolves the
    callable."""

    def __init__(self, scan_dirs: Callable[[], List[str]],
                 specs: Sequence[Mapping[str, Any]],
                 ledger_dir: str,
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: Optional[float] = None,
                 scrape_timeout: float = 1.0):
        self._scan_dirs = scan_dirs
        self._registry = registry or get_registry()
        self._interval = float(
            interval_s if interval_s is not None
            else knob_float("EASYDL_ALERT_EVAL_INTERVAL_S"))
        self._timeout = float(scrape_timeout)
        self.ledger_dir = ledger_dir
        self.evaluator = AlertEvaluator(
            specs, ledger_dir=ledger_dir, registry=self._registry)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.rounds = 0
        #: [{"slo", "to", "t"}] — wall-stamped state changes
        self.transitions: List[Dict[str, Any]] = []
        self.scrape_stats = {"attempts": 0, "failures": 0}
        self._swept: List[str] = []

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "AlertRecorder":
        self._thread = threading.Thread(
            target=self._run, name="alert-recorder", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        with self._lock:
            self._tick()  # final state AFTER recovery settled
        self.evaluator.close()
        return self.evidence()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            with self._lock:
                try:
                    self._tick()
                except Exception as e:  # witness must never kill a drill
                    log.warning("alert recorder tick failed: %s", e)

    # ----------------------------------------------------------- one round
    def _discover(self) -> Dict[str, Dict[str, Any]]:
        from easydl_tpu.obs import scrape

        docs: Dict[str, Dict[str, Any]] = {}
        for d in self._scan_dirs():
            for component, doc in scrape.discover_docs(d).items():
                if doc.get("pid") == os.getpid():
                    continue  # in-process registries are read directly
                doc = dict(doc, _dir=os.path.join(d, OBS_DIR))
                docs[f"{os.path.basename(d) or 'root'}/{component}"] = doc
        return docs

    def _sweep(self, doc: Mapping[str, Any]) -> None:
        """Retire a failed target's discovery doc IFF its pid is dead —
        the scrape-side mirror of exporter._sweep_stale (see module
        docstring). Counting happened before this call."""
        pid = doc.get("pid")
        addr = str(doc.get("address", ""))
        host = addr.rsplit(":", 1)[0] if ":" in addr else ""
        if not isinstance(pid, int) or host not in ("127.0.0.1",
                                                    "localhost"):
            return
        try:
            os.kill(pid, 0)
            # The pid exists — but a SIGKILLed child is a ZOMBIE until its
            # parent reaps it, and a zombie holds no sockets: its exporter
            # is gone for good. Waiting for the reap would keep the scrape
            # failure counter climbing (and the scrape-health page pinned)
            # for as long as the parent is busy. A live (maybe SIGSTOPped)
            # process keeps failing instead — not swept.
            if not _is_zombie(pid):
                return
        except ProcessLookupError:
            pass
        except OSError:
            return
        path = os.path.join(str(doc.get("_dir", "")),
                            f"{doc.get('component')}.json")
        try:
            os.unlink(path)
            self._swept.append(path)
        except OSError:
            pass

    def _tick(self) -> None:
        from easydl_tpu.obs import scrape

        docs = self._discover()
        targets = {key: str(doc.get("address", ""))
                   for key, doc in docs.items() if doc.get("address")}
        scraped = scrape.scrape_fleet(targets, timeout=self._timeout) \
            if targets else {}
        for key, result in scraped.items():
            self.scrape_stats["attempts"] += 1
            if not result.get("ok"):
                self.scrape_stats["failures"] += 1
                self._sweep(docs[key])
        # In-process registry AFTER the scrape: this tick's scrape
        # failure counters are visible to this tick's decision.
        merged: Dict[str, float] = dict(self._registry.samples())
        for key, result in sorted(scraped.items()):
            if not result.get("ok"):
                continue
            for series, value in result["metrics"].items():  # type: ignore[union-attr]
                if series in merged and scrape._is_additive(series):
                    merged[series] += float(value)
                else:
                    merged[series] = float(value)
        now = time.time()
        decision = self.evaluator.tick(merged, now)
        self.rounds += 1
        for tr in decision["transitions"]:
            self.transitions.append(dict(tr, t=round(now, 6)))

    # ------------------------------------------------------------ evidence
    def evidence(self) -> Dict[str, Any]:
        firing: Dict[str, float] = {}
        first_fire: Dict[str, float] = {}
        cleared: Dict[str, bool] = {}
        for tr in self.transitions:
            slo = str(tr["slo"])
            if tr["to"] == "firing":
                first_fire.setdefault(slo, float(tr["t"]))
                firing[slo] = float(tr["t"])
                cleared[slo] = False
            else:
                cleared[slo] = True
        alerts = dict(self.evaluator.last.get("alerts") or {})
        pages = sorted({
            str(tr["slo"]) for tr in self.transitions
            if tr["to"] == "firing"
            and alerts.get(str(tr["slo"]), {}).get("severity",
                                                   "page") == "page"})
        return {
            "rounds": self.rounds,
            "interval_s": self._interval,
            "transitions": self.transitions,
            "first_fire": {k: round(v, 6)
                           for k, v in sorted(first_fire.items())},
            "cleared": cleared,
            "firing_final": sorted(
                n for n, a in alerts.items() if a.get("active")),
            "pages_fired": pages,
            "decisions": len(self.evaluator.policy.log),
            "replay": replay_ledger(self.ledger_dir),
            "scrape": dict(self.scrape_stats, swept=list(self._swept)),
        }


def decision_digest(records: Sequence[Mapping[str, Any]]) -> str:
    """Stable digest over a decision log's verdict bytes (fixture
    pinning for the fleet-scale sim)."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for rec in records:
        h.update(decision_bytes(rec.get("verdict") or {}))
    return h.hexdigest()
