"""Compile-on-first-use loader for the framework's C++ cores.

No pip/pybind11 in the image, so native components (the PS embedding store,
the controller's reconciler core — the C++ surfaces the reference anticipated
via its clang-format/cpplint hooks, .pre-commit-config.yaml:24-41) are built
with ``g++`` into shared libraries on first use and cached next to their
source, keyed by a hash of source + flags. Concurrent builders race safely:
each writes a unique temp file and ``os.replace``\\ s it into place.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from typing import Callable, Dict, Optional

from easydl_tpu.utils.logging import get_logger

log = get_logger("utils", "native")

CXXFLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-Wall"]
#: Link libs, placed AFTER the source on the command line. librt is the
#: shm_open/shm_unlink home on this image's glibc (2.31 — merged into libc
#: only from 2.34); linking it elsewhere is a no-op.
LDLIBS = ["-lpthread", "-lrt"]

_cache: Dict[str, Optional[ctypes.CDLL]] = {}


def _compile(source: str, target: str) -> None:
    os.makedirs(os.path.dirname(target), exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(target))
    os.close(fd)
    try:
        subprocess.run(
            ["g++", *CXXFLAGS, "-o", tmp, source, *LDLIBS],
            check=True, capture_output=True, text=True,
        )
        os.replace(tmp, target)  # atomic; last concurrent builder wins
        log.info("compiled %s", os.path.basename(target))
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"g++ failed building {os.path.basename(source)}:\n{e.stderr}"
        ) from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_native(source: str, bind: Callable[[ctypes.CDLL], None]) -> Optional[ctypes.CDLL]:
    """Compile (if needed) and load ``source``; ``bind`` sets argtypes.
    Returns None when no toolchain is available — callers fall back to their
    pure-Python twin. The result (including failure) is cached per source."""
    if source in _cache:
        return _cache[source]
    lib: Optional[ctypes.CDLL] = None
    if shutil.which("g++") is None:
        log.warning("no g++ in PATH — %s uses its Python fallback",
                    os.path.basename(source))
    else:
        try:
            with open(source, "rb") as f:
                digest = hashlib.sha256(
                    f.read() + " ".join(CXXFLAGS + LDLIBS).encode()
                ).hexdigest()[:16]
            base = os.path.splitext(os.path.basename(source))[0]
            path = os.path.join(
                os.path.dirname(source), "_build", f"{base}-{digest}.so"
            )
            if not os.path.exists(path):
                _compile(source, path)
            lib = ctypes.CDLL(path)
            bind(lib)
        except (RuntimeError, OSError) as e:
            log.warning("native %s unavailable (%s) — Python fallback",
                        os.path.basename(source), e)
            lib = None
    _cache[source] = lib
    return lib
