"""Model zoo — JAX/Flax models covering the BASELINE configs.

Reference anchor: the quickstart `model_zoo.iris.dnn_estimator`
(docs/design/elastic-training-operator.md:37) and the BASELINE.json families:
MLP, ResNet-50, BERT-base, GPT-2 345M, DeepFM/Wide&Deep.
"""

from easydl_tpu.models.registry import get_model, register_model, list_models  # noqa: F401
