"""Chunked fused LM loss (ops/fused_xent.py): numerics must match the naive
optax path exactly (same formula, f32 accumulation) and gradients must flow
to both hidden states and the head — this is the lever that removes the
[B,S,V] f32 logits buffer capping bench microbatch/MFU (VERDICT r2 item 6)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from easydl_tpu.models import get_model
from easydl_tpu.models.gpt import lm_loss
from easydl_tpu.ops.fused_xent import fused_softmax_xent


def naive(hidden, head, targets, ignore_id=-1):
    logits = (hidden @ head.T).astype(jnp.float32)
    mask = (targets != ignore_id).astype(jnp.float32)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(targets, 0)
    )
    denom = jnp.maximum(mask.sum(), 1.0)
    return (losses * mask).sum() / denom


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("seq,chunk", [(64, 16), (60, 16), (8, 128)],
                         ids=["even", "ragged-pad", "chunk>seq"])
def test_matches_naive_loss_and_grads(dtype, seq, chunk):
    rng = np.random.RandomState(0)
    B, D, V = 4, 32, 96
    hidden = jnp.asarray(rng.randn(B, seq, D), jnp.dtype(dtype))
    head = jnp.asarray(rng.randn(V, D) * 0.1, jnp.dtype(dtype))
    targets = jnp.asarray(rng.randint(0, V, (B, seq)), jnp.int32)
    # mask a few positions
    targets = targets.at[:, :3].set(-1)

    loss_f, denom = fused_softmax_xent(hidden, head, targets,
                                       chunk_size=chunk)
    loss_n = naive(hidden, head, targets)
    # bf16: the fused op keeps f32 accumulation (preferred_element_type)
    # where the naive bf16 matmul rounds its output to bf16 — the fused
    # result is the more accurate one, so the comparison needs bf16 slack.
    np.testing.assert_allclose(float(loss_f), float(loss_n),
                               rtol=2e-6 if dtype == "float32" else 1e-3)
    assert float(denom) == B * (seq - 3)

    g_f = jax.grad(
        lambda h, w: fused_softmax_xent(h, w, targets, chunk_size=chunk)[0],
        argnums=(0, 1),
    )(hidden, head)
    g_n = jax.grad(
        lambda h, w: naive(h, w, targets), argnums=(0, 1)
    )(hidden, head)
    tol = 1e-5 if dtype == "float32" else 2e-2
    for a, b in zip(g_f, g_n):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=tol, atol=tol)


def test_all_masked_is_finite():
    hidden = jnp.ones((2, 8, 16), jnp.float32)
    head = jnp.ones((32, 16), jnp.float32)
    targets = jnp.full((2, 8), -1, jnp.int32)
    loss, denom = fused_softmax_xent(hidden, head, targets, chunk_size=4)
    assert float(loss) == 0.0 and float(denom) == 1.0


def test_gpt_bundle_fused_matches_logits_path(eight_devices):
    """End-to-end through the model: the fused-loss bundle and the logits
    bundle compute the same loss and the same gradients on the same params."""
    kw = dict(size="test", seq_len=64, vocab=256)
    fused = get_model("gpt", fused_loss=True, loss_chunk=16, **kw)
    plain = get_model("gpt", fused_loss=False, **kw)
    rng = jax.random.PRNGKey(0)
    params = fused.init_fn(rng)
    batch = next(iter(plain.make_data(4, seed=3)))

    lf, mf = fused.loss_fn(params, batch, rng)
    lp, mp = plain.loss_fn(params, batch, rng)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-6)
    np.testing.assert_allclose(float(mf["perplexity"]),
                               float(mp["perplexity"]), rtol=1e-6)

    gf = jax.grad(lambda p: fused.loss_fn(p, batch, rng)[0])(params)
    gp = jax.grad(lambda p: plain.loss_fn(p, batch, rng)[0])(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_gpt_moe_fused_loss_runs(eight_devices):
    bundle = get_model("gpt", size="test", seq_len=32, vocab=128,
                       moe_experts=4, fused_loss=True, loss_chunk=8)
    rng = jax.random.PRNGKey(1)
    params = bundle.init_fn(rng)
    batch = next(iter(bundle.make_data(4, seed=5)))
    loss, metrics = bundle.loss_fn(params, batch, rng)
    assert np.isfinite(float(loss))
    assert "moe_balance" in metrics
