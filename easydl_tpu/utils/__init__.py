"""Shared plumbing: structured logging, gRPC service helpers, and the
compile-and-cache loader for the C++ cores."""

from easydl_tpu.utils.logging import get_logger  # noqa: F401
