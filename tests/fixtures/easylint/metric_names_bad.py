"""Known-bad fixture: metric registrations breaking the easydl_*
conventions — the metric-name rule MUST flag every marked site."""

from easydl_tpu.obs.registry import get_registry

reg = get_registry()

C1 = reg.counter("easydl_serve_hits", "no _total")        # FLAG
C2 = reg.counter("Easydl-Serve-Hits_total", "grammar")    # FLAG
C3 = reg.counter("hits_total", "no easydl_ prefix")       # FLAG
H1 = reg.histogram("easydl_serve_wait", "no unit")        # FLAG
G1 = reg.gauge("easydl_serve_depth", "reserved", ("le",))           # FLAG
G2 = reg.gauge("easydl_serve_depth2", "unknown", ("made_up_lbl",))  # FLAG

_name = "easydl_" + "serve_dyn_total"
C4 = reg.counter(_name, "unverifiable")                   # FLAG
