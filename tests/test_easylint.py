"""easylint: per-rule fixture proofs, baseline round-trip, the tier-1
whole-tree gate, the CLI contract, and the knob doc-sync check.

Anti-vacuous by construction (same style as the chaos invariants'
negative controls): every rule must FIRE on its known-bad fixture —
with the exact expected details — and stay QUIET on the adjacent
known-good fixture, so a rule that silently stops matching cannot pass.
"""

import ast
import os
import subprocess
import sys

import pytest

from easydl_tpu.analysis import baseline as bl
from easydl_tpu.analysis.core import (
    analyze_file,
    analyze_paths,
    collect_files,
)
from easydl_tpu.analysis.rules import (
    BlockingCallUnderLock,
    CountedSwallow,
    KnobRegistry,
    MetricNameLint,
    NakedRpc,
    SloMetricRefs,
    VirtualClockPurity,
    all_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "easylint")
BASELINE = os.path.join(REPO, "scripts", "codestyle",
                        "easylint_baseline.txt")


def run_rule(rule, fixture, fake_path):
    """Run one rule over a fixture file under a pretend repo path (rules
    scope by path: swallow to easydl_tpu/, purity to sim/, …)."""
    with open(os.path.join(FIXTURES, fixture), encoding="utf-8") as f:
        src = f.read()
    return rule.check(fake_path, ast.parse(src), src)


FIXTURE_KNOBS = ("EASYDL_FIXTURE_KNOB",)

#: (rule factory, fixture stem, fake repo path, details the bad fixture
#: MUST produce — a subset check, exact names).
CASES = [
    (BlockingCallUnderLock, "locks", "easydl_tpu/ps/fake.py",
     {"time.sleep", "subprocess.run", "rpc:Pull", "wal-append"}),
    (NakedRpc, "naked_rpc", "easydl_tpu/elastic/fake.py",
     {"grpc.insecure_channel", "grpc.server", "stub-factory:unary_unary"}),
    (lambda: KnobRegistry(declared=FIXTURE_KNOBS), "knobs",
     "easydl_tpu/ps/fake.py",
     {"EASYDL_FIXTURE_KNOB",
      "undeclared-knob:EASYDL_FIXTURE_UNDECLARED"}),
    (CountedSwallow, "swallow", "easydl_tpu/ps/fake.py",
     {"silent-swallow", "bare-except"}),
    (VirtualClockPurity, "purity", "easydl_tpu/sim/fake.py",
     {"time.time", "random.random", "time.monotonic"}),
    (MetricNameLint, "metric_names", "easydl_tpu/serve/fake.py",
     {"counter-no-total:easydl_serve_hits",
      "bad-name:Easydl-Serve-Hits_total",
      "bad-name:hits_total",
      "histogram-no-unit:easydl_serve_wait",
      "bad-label:le",
      "unknown-label:made_up_lbl",
      "unverifiable-name"}),
    (SloMetricRefs, "slo_refs", "easydl_tpu/brain/alert_policy.py",
     {"unknown-series:easydl_serve_router_request_total",
      "unknown-series:easydl_made_up_family_total"}),
]


@pytest.mark.parametrize(
    "make_rule,stem,path,expected",
    CASES, ids=[c[1] for c in CASES])
def test_rule_fires_on_bad_fixture(make_rule, stem, path, expected):
    findings = run_rule(make_rule(), f"{stem}_bad.py", path)
    details = {f.detail for f in findings}
    missing = expected - details
    assert not missing, (
        f"{stem}: rule failed to flag known-bad sites {missing}; "
        f"got {sorted(details)}")


@pytest.mark.parametrize(
    "make_rule,stem,path,expected",
    CASES, ids=[c[1] for c in CASES])
def test_rule_quiet_on_good_fixture(make_rule, stem, path, expected):
    findings = run_rule(make_rule(), f"{stem}_good.py", path)
    assert findings == [], (
        f"{stem}: rule flagged known-good code: "
        f"{[f.render() for f in findings]}")


def test_knob_bad_fixture_flags_every_inline_read_form():
    rule = KnobRegistry(declared=FIXTURE_KNOBS)
    findings = run_rule(rule, "knobs_bad.py", "easydl_tpu/ps/fake.py")
    # .get / [subscript] / os.getenv / constant / mapping-param + the
    # undeclared accessor: six distinct sites
    assert len(findings) == 6, [f.render() for f in findings]


def test_swallow_rule_scoped_to_easydl_tpu():
    # the same bad code outside easydl_tpu/ is out of the rule's scope
    assert run_rule(CountedSwallow(), "swallow_bad.py",
                    "scripts/fake.py") == []


def test_purity_rule_scoped_to_replayed_modules():
    assert run_rule(VirtualClockPurity(), "purity_bad.py",
                    "easydl_tpu/elastic/agent_like.py") == []


def test_naked_rpc_allowed_inside_blessed_seams():
    assert run_rule(NakedRpc(), "naked_rpc_bad.py",
                    "easydl_tpu/utils/rpc.py") == []


def test_slo_refs_scoped_to_alerting_modules():
    # the same unknown-family literals outside obs/slo.py, obs/alerts.py
    # and brain/alert_policy.py are out of the rule's scope
    assert run_rule(SloMetricRefs(), "slo_refs_bad.py",
                    "easydl_tpu/serve/fake.py") == []


def test_slo_refs_yaml_catalog_half():
    """Analyzing the anchor module resolves every slos/*.yaml: unknown
    selector families and loader-invalid specs are findings anchored on
    the YAML file, and a clean catalog stays quiet."""
    bad = SloMetricRefs(slos_dir=os.path.join(FIXTURES, "slos_bad"))
    findings = bad.check("easydl_tpu/obs/slo.py", ast.parse(""), "")
    details = {f.detail for f in findings}
    assert "unknown-series:easydl_no_such_family_total" in details
    assert "invalid-slo:invalid.yaml" in details
    assert {f.path for f in findings} == {
        "slos/unknown_series.yaml", "slos/invalid.yaml"}

    good = SloMetricRefs(slos_dir=os.path.join(FIXTURES, "slos_good"))
    assert good.check("easydl_tpu/obs/slo.py", ast.parse(""), "") == []


def test_committed_slo_catalog_resolves_against_registry():
    """The repo's own slos/ directory rides the anchor in the tree gate;
    assert it directly too so a catalog regression names this test."""
    findings = SloMetricRefs().check(
        "easydl_tpu/obs/slo.py", ast.parse(""), "")
    assert findings == [], "\n".join(f.render() for f in findings)


def _scan_registered_names():
    """AST-scan every registration site in easydl_tpu/ for the literal
    metric name; the rpc ``f"easydl_rpc_{side}_*"`` family expands over
    side in client/server. Any other dynamic name is a hard failure
    (the metric-name rule flags it too — this keeps the scan honest)."""
    from easydl_tpu.analysis.core import dotted_name

    expansions = {"side": ("client", "server")}
    names, unverifiable = set(), []
    for path in collect_files(["easydl_tpu"], root=REPO):
        with open(os.path.join(REPO, path), encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge", "histogram")
                    and node.args):
                continue
            recv = (dotted_name(node.func.value) or "").lower()
            if not ("reg" in recv.rsplit(".", 1)[-1]
                    or isinstance(node.func.value, ast.Call)):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names.add(arg.value)
            elif isinstance(arg, ast.JoinedStr):
                variants = [""]
                for part in arg.values:
                    if (isinstance(part, ast.Constant)
                            and isinstance(part.value, str)):
                        variants = [v + part.value for v in variants]
                    elif (isinstance(part, ast.FormattedValue)
                          and isinstance(part.value, ast.Name)
                          and part.value.id in expansions):
                        variants = [v + sub for v in variants
                                    for sub in expansions[part.value.id]]
                    else:
                        unverifiable.append(f"{path}:{node.lineno}")
                        variants = []
                        break
                names.update(variants)
            else:
                unverifiable.append(f"{path}:{node.lineno}")
    assert not unverifiable, (
        f"registration sites with names the sync scan cannot expand: "
        f"{unverifiable}")
    return names


def test_registered_metrics_matches_registration_sites():
    """REGISTERED_METRICS (what slo-metric-refs resolves SLO selectors
    against) is exactly the set of families the tree registers — a stale
    entry and an undeclared registration both fail, in both directions."""
    from easydl_tpu.analysis.rules.metric_names import REGISTERED_METRICS

    scanned = _scan_registered_names()
    stale = REGISTERED_METRICS - scanned
    undeclared = scanned - REGISTERED_METRICS
    assert not stale, (
        f"REGISTERED_METRICS entries with no registration site (delete "
        f"them): {sorted(stale)}")
    assert not undeclared, (
        f"registration sites missing from REGISTERED_METRICS (declare "
        f"them): {sorted(undeclared)}")


# ------------------------------------------------------------------ baseline
def test_baseline_round_trip(tmp_path):
    path = str(tmp_path / "base.txt")
    entries = [
        bl.BaselineEntry("r", "a.py", "f", "d", "because reasons"),
        bl.BaselineEntry("r", "a.py", "f", "d#2", "also reasons"),
    ]
    bl.save(path, entries)
    loaded = bl.load(path)
    assert sorted(e.render() for e in loaded) == \
        sorted(e.render() for e in entries)
    # save() sorts and dedupes
    bl.save(path, entries + entries)
    assert len(bl.load(path)) == 2


def test_baseline_rejects_missing_reason(tmp_path):
    path = tmp_path / "base.txt"
    path.write_text("rule|p.py|scope|detail|   \n")
    with pytest.raises(ValueError):
        bl.load(str(path))


def test_baseline_match_multiset_and_stale():
    from easydl_tpu.analysis.core import Finding

    f = Finding("r", "a.py", 1, "f", "d", "m")
    have = [bl.BaselineEntry("r", "a.py", "f", "d", "why"),
            bl.BaselineEntry("r", "b.py", "g", "d", "why")]
    new, stale = bl.match([f, f], have)
    # one consumed, one finding new, one entry stale
    assert len(new) == 1 and new[0].key() == f.key()
    assert [e.path for e in stale] == ["b.py"]


def test_update_preserves_reasons_and_stamps_new():
    from easydl_tpu.analysis.core import Finding

    old = [bl.BaselineEntry("r", "a.py", "f", "d", "human reason")]
    findings = [Finding("r", "a.py", 1, "f", "d", "m"),
                Finding("r", "a.py", 9, "g", "d", "m")]
    merged = bl.updated(findings, old)
    reasons = {(e.scope): e.reason for e in merged}
    assert reasons["f"] == "human reason"
    assert reasons["g"] == bl.TODO_REASON


# ------------------------------------------------------------------ CLI
def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "easylint.py")]
        + args, capture_output=True, text=True, cwd=cwd)


def test_cli_gate_and_update_baseline(tmp_path):
    root = tmp_path / "repo"
    (root / "easydl_tpu").mkdir(parents=True)
    bad = root / "easydl_tpu" / "mod.py"
    bad.write_text('"""Doc."""\n\n\ndef f(c):\n    try:\n        c()\n'
                   "    except Exception:\n        pass\n")
    base = str(root / "base.txt")

    # new finding → exit 1, reported on stdout
    r = _run_cli(["--root", str(root), "--baseline", base, "easydl_tpu"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "counted-swallow" in r.stdout

    # --update-baseline writes TODO-stamped entries and exits 0 …
    r = _run_cli(["--root", str(root), "--baseline", base,
                  "--update-baseline", "easydl_tpu"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert bl.TODO_REASON in open(base).read()

    # … but the gate refuses TODO reasons until a human writes one
    r = _run_cli(["--root", str(root), "--baseline", base, "easydl_tpu"])
    assert r.returncode == 1
    assert "lacks a reason" in r.stderr

    content = open(base).read().replace(bl.TODO_REASON, "fixture says so")
    open(base, "w").write(content)
    r = _run_cli(["--root", str(root), "--baseline", base, "easydl_tpu"])
    assert r.returncode == 0, r.stdout + r.stderr

    # fixing the violation turns the entry stale (warned, exit still 0)
    bad.write_text('"""Doc."""\n\n\ndef f(c):\n    c()\n')
    r = _run_cli(["--root", str(root), "--baseline", base, "easydl_tpu"])
    assert r.returncode == 0
    assert "stale" in r.stderr


# ------------------------------------------------------------- tier-1 gate
def test_tree_is_clean_against_committed_baseline():
    """THE gate: zero un-baselined findings over easydl_tpu/ + scripts/,
    zero stale allowlist entries, zero TODO reasons — the committed
    baseline can only shrink unless a reviewed reason is added."""
    findings = analyze_paths(["easydl_tpu", "scripts"], all_rules(),
                             root=REPO)
    entries = bl.load(BASELINE)
    new, stale = bl.match(findings, entries)
    assert new == [], "un-baselined findings:\n" + "\n".join(
        f.render() for f in new)
    assert stale == [], (
        "stale baseline entries (violation fixed — delete the line / run "
        "--update-baseline):\n" + "\n".join(e.render() for e in stale))
    todo = [e for e in entries if e.reason == bl.TODO_REASON]
    assert todo == [], "baseline entries lack a human reason"


def test_generated_proto_is_excluded():
    files = collect_files(["easydl_tpu"], root=REPO)
    assert "easydl_tpu/proto/easydl_pb2.py" not in files
    assert "easydl_tpu/analysis/core.py" in files  # analyzer lints itself


def test_syntax_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = analyze_file(str(p), all_rules(), root=str(tmp_path))
    assert [f.rule for f in findings] == ["parse"]


# ------------------------------------------------------------- knob docs
def _declared_knob_names():
    env_py = os.path.join(REPO, "easydl_tpu", "utils", "env.py")
    tree = ast.parse(open(env_py, encoding="utf-8").read())
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "KNOB_DECLS"):
            decls = ast.literal_eval(stmt.value)
            return [d[0] for d in decls]
    raise AssertionError("KNOB_DECLS literal not found in utils/env.py")


def test_knob_decls_is_a_pure_literal_with_valid_shape():
    names = _declared_knob_names()
    assert len(names) == len(set(names)), "duplicate knob declarations"
    from easydl_tpu.utils.env import KNOBS

    assert set(KNOBS) == set(names)
    for name in names:
        assert name.startswith("EASYDL_"), name
    types = {k.type for k in KNOBS.values()}
    assert types <= {"str", "int", "float", "bool"}


def test_knob_doc_sync():
    """Every declared knob appears in the docs/operations.md knob table
    and every EASYDL_* table row is declared — both directions, so the
    operator docs cannot rot."""
    import re

    declared = set(_declared_knob_names())
    doc = open(os.path.join(REPO, "docs", "operations.md"),
               encoding="utf-8").read()
    rows = set(re.findall(r"^\| *`(EASYDL_[A-Z0-9_*]+)`", doc,
                          flags=re.M))
    missing_doc = declared - rows
    assert not missing_doc, (
        f"knobs declared in utils/env.py but missing from the "
        f"docs/operations.md knob table: {sorted(missing_doc)}")
    undeclared = rows - declared
    assert not undeclared, (
        f"knob table rows in docs/operations.md not declared in "
        f"utils/env.py KNOB_DECLS: {sorted(undeclared)}")


def test_typed_accessors(monkeypatch):
    from easydl_tpu.utils import env

    monkeypatch.setenv("EASYDL_PS_WAL_SYNC_S", "1.5")
    assert env.knob_float("EASYDL_PS_WAL_SYNC_S") == 1.5
    monkeypatch.delenv("EASYDL_PS_WAL_SYNC_S", raising=False)
    assert env.knob_float("EASYDL_PS_WAL_SYNC_S") == 0.2  # declared default
    assert env.knob_float("EASYDL_PS_WAL_SYNC_S", 9.0) == 9.0  # override
    # bool grammar matches env_flag
    monkeypatch.setenv("EASYDL_PS_WAL", "0")
    assert env.knob_bool("EASYDL_PS_WAL") is False
    monkeypatch.setenv("EASYDL_PS_WAL", "yes")
    assert env.knob_bool("EASYDL_PS_WAL") is True
    # mapping-parameter reads (the agent->worker IPC idiom)
    assert env.knob_int("EASYDL_RANK", env={"EASYDL_RANK": "3"}) == 3
    with pytest.raises(KeyError):
        env.knob_int("EASYDL_RANK", env={})  # required knob
    # family declarations resolve by prefix
    assert env.knob_raw("EASYDL_METRICS_PORT_PS_0",
                        env={"EASYDL_METRICS_PORT_PS_0": "1"}) == "1"
    with pytest.raises(KeyError):
        env.knob_raw("EASYDL_NOT_DECLARED_ANYWHERE")


def test_cli_fails_loudly_on_missing_path(tmp_path):
    """Regression: a typo'd path must not analyze zero files and exit 0."""
    r = _run_cli(["--root", str(tmp_path), "--baseline",
                  str(tmp_path / "b.txt"), "no_such_dir"])
    assert r.returncode == 1
    assert "no such file or directory" in r.stderr
