"""Profiling hooks: XLA traces + step annotations (SURVEY.md §5.1).

The reference promises performance monitoring (README.md:21-23) with no
mechanism; the coarse per-step pipeline here is
:class:`easydl_tpu.core.metrics.MetricsRecorder` → Brain. This module is the
deep-dive layer on top: ``jax.profiler`` device traces viewable in
TensorBoard/Perfetto (compute/communication overlap, HBM, per-op time) and
named step/phase annotations that show up inside those traces.

Usage::

    with trace("/tmp/profile"):          # whole-region trace
        for step in range(10):
            with step_annotation("train", step):
                state, m = trainer.train_step(state, batch)

    prof = StepProfiler("/tmp/profile", start_step=5, num_steps=3)
    for step in range(20):
        prof.maybe_start(step)           # traces only steps [5, 8)
        ...
        prof.maybe_stop(step)
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

from easydl_tpu.utils.logging import get_logger

log = get_logger("utils", "profiling")


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XLA device trace for the enclosed region."""
    jax.profiler.start_trace(logdir)
    log.info("profiler trace started -> %s", logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written -> %s", logdir)


def step_annotation(name: str, step: Optional[int] = None):
    """Label the enclosed work in the trace timeline (StepTraceAnnotation
    when a step number is given, else a named TraceAnnotation)."""
    if step is not None:
        return jax.profiler.StepTraceAnnotation(name, step_num=step)
    return jax.profiler.TraceAnnotation(name)


class StepProfiler:
    """Window-triggered tracing inside a training loop: skips compile/warmup
    steps and captures exactly ``num_steps`` steady-state steps."""

    def __init__(self, logdir: str, start_step: int = 5, num_steps: int = 3):
        self.logdir = logdir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False
        self._done = False

    def maybe_start(self, step: int) -> None:
        if not self._done and not self._active and step >= self.start_step:
            jax.profiler.start_trace(self.logdir)
            self._active = True
            log.info("profiling steps [%d, %d) -> %s", step, self.stop_step,
                     self.logdir)

    def maybe_stop(self, step: int) -> None:
        if self._active and step + 1 >= self.stop_step:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True


# --------------------------------------------------------------- attribution
#
# Chrome-trace parsing for scripts/bench_profile.py. Split out here (pure
# stdlib, no jax at call time) so the attribution logic is unit-testable
# against synthetic traces — the round-4 artifact was internally
# incoherent precisely because the parser ran only against real traces it
# could misread (umbrella events double-counted, while-bodies opaque,
# busy > span so the "gap" went to -184%).


def categorize_op(name: str, args: Optional[dict] = None) -> str:
    """Category for one DEVICE op event.

    The specific name signal wins over the profiler's generic hlo
    category: flash-attention kernels ARE custom calls and the profiler
    tags them so — letting a 'custom' category preempt the name check
    would re-create the r4 symptom (flash attributed ~0, lumped into
    custom_call). Generic categories then refine whatever the name
    doesn't pin down."""
    n = name.lower()
    if "flash" in n:
        return "flash_attention"
    if args:
        for key in ("hlo_category", "category"):
            cat = str(args.get(key, "")).lower()
            if cat:
                if "convolution" in cat or "dot" in cat or "gemm" in cat:
                    return "matmul"
                if "custom" in cat:
                    return "custom_call"
                if "all-reduce" in cat or "all-gather" in cat \
                        or "collective" in cat or "reduce-scatter" in cat:
                    return "collectives"
    if "custom-call" in n or "custom_call" in n:
        return "custom_call"
    if ("all-reduce" in n or "all-gather" in n or "reduce-scatter" in n
            or "collective" in n or "ppermute" in n or "all-to-all" in n):
        return "collectives"
    if n.startswith(("dot", "convolution")) or "gemm" in n or "einsum" in n:
        return "matmul"
    if "dynamic-update-slice" in n or "dynamic_update_slice" in n:
        return "dus_carry"
    if "fusion" in n:
        # XLA fuses elementwise chains into the producing/consuming op;
        # matmul-rooted fusions usually keep 'dot' in the name
        if "dot" in n or "conv" in n:
            return "matmul_fusion"
        if "dynamic-update-slice" in n or "dus" in n:
            return "dus_carry"
        if "reduce" in n:
            return "reduction_fusion"
        return "other_fusion"
    if "infeed" in n or "outfeed" in n or "copy" in n or "transpose" in n:
        return "data_movement"
    if "scan" in n or n.startswith("while") or "conditional" in n:
        return "control_flow"
    return "other"


#: Event names that are wrappers around real device work — a jit program,
#: a module, a named step region. Their SELF time (gaps not covered by any
#: child op) is reported as "unattributed_parent", never as op work.
_UMBRELLA_MARKERS = ("jit_", "jit(", "module", "program", "xlamodule")


def _is_umbrella(name: str) -> bool:
    n = name.lower()
    return n.startswith(_UMBRELLA_MARKERS) or n in ("train_step", "step")


def _self_times(events):
    """Self time per event for one lane of Chrome X events.

    Events may nest (a fusion inside a while inside a jit program); the
    Chrome format encodes nesting purely by interval containment on the
    same (pid, tid). Sorting by (ts, -dur) and keeping a stack of open
    intervals yields each event's direct parent; a child's duration is
    subtracted from its parent so every microsecond is attributed exactly
    once. Returns [(name, self_us, had_children)]."""
    evs = sorted(events, key=lambda e: (e["ts"], -e["dur"]))
    out = []
    stack = []  # indices into out; [(end_ts, out_idx)]
    for e in evs:
        ts, dur = e["ts"], e["dur"]
        while stack and ts >= stack[-1][0] - 1e-9:
            stack.pop()
        out_idx = len(out)
        out.append([e["name"], dur, False, e.get("args") or {}])
        if stack:
            parent = out[stack[-1][1]]
            parent[1] -= dur
            parent[2] = True
        stack.append((ts + dur, out_idx))
    return [(n, max(s, 0.0), c, a) for n, s, c, a in out]


def _union_us(events) -> float:
    """Total covered time of a lane — union of [ts, ts+dur), overlap-safe
    (nested events must not inflate 'busy' past the wall span)."""
    iv = sorted((e["ts"], e["ts"] + e["dur"]) for e in events)
    total, cur_lo, cur_hi = 0.0, None, None
    for lo, hi in iv:
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def attribute_trace(trace_doc: dict, top: int = 15) -> dict:
    """Attribute device time from one Chrome-trace document.

    Picks the busiest DEVICE ops lane (thread named like 'XLA Ops' under a
    TPU/device process; falls back to the busiest thread of any device
    process), computes per-op SELF time (children subtracted), categorizes
    leaves, and reports invariants instead of trusting itself:

    - categories (incl. unattributed_parent) sum to the lane's busy time;
    - busy is an interval union, so gap_pct ∈ [0, 100].
    """
    events = trace_doc.get("traceEvents", [])
    pid_names, tid_names = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", ""))
    device_pids = {
        pid for pid, label in pid_names.items()
        if "tpu" in label.lower() or "/device" in label.lower()
        or "gpu" in label.lower()
    }
    if not device_pids:
        device_pids = set(pid_names) or {
            e.get("pid") for e in events if e.get("ph") == "X"}

    lanes = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        key = (e["pid"], e.get("tid"))
        lanes.setdefault(key, []).append({
            "name": e.get("name", "?"),
            "ts": float(e.get("ts", 0.0)),
            "dur": float(e.get("dur", 0.0)),
            "args": e.get("args"),
        })
    if not lanes:
        return {"error": "no device X events in trace"}

    ops_lanes = [
        k for k in lanes if "xla ops" in tid_names.get(k, "").lower()
    ]
    candidates = ops_lanes or list(lanes)
    busiest = max(candidates, key=lambda k: _union_us(lanes[k]))
    lane = lanes[busiest]

    selfs = _self_times(lane)
    cats: dict = {}
    per_op: dict = {}
    for name, self_us, had_children, args in selfs:
        if _is_umbrella(name):
            # wrapper self-time = device time no leaf op covers
            cats["unattributed_parent"] = (
                cats.get("unattributed_parent", 0.0) + self_us)
            continue
        # Non-umbrella parents (a while op around its body, a fused region
        # around sub-ops) keep their own SELF time under their own category
        # — that's genuine loop/dispatch overhead, not their children's work.
        cat = categorize_op(name, args)
        cats[cat] = cats.get(cat, 0.0) + self_us
        per_op[name] = per_op.get(name, 0.0) + self_us

    busy_us = _union_us(lane)
    span = (min(e["ts"] for e in lane),
            max(e["ts"] + e["dur"] for e in lane))
    span_us = span[1] - span[0]
    cat_sum = sum(cats.values())
    gap_pct = 100.0 * (1.0 - busy_us / span_us) if span_us else 0.0
    invariants = {
        "categories_sum_us": round(cat_sum, 1),
        "lane_busy_us": round(busy_us, 1),
        "categories_cover_busy": bool(
            busy_us == 0 or abs(cat_sum - busy_us) / busy_us < 0.02),
        "gap_pct_in_range": bool(-1e-6 <= gap_pct <= 100.0),
    }
    top_ops = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    return {
        "lane": f"{pid_names.get(busiest[0], busiest[0])}"
                f" / {tid_names.get(busiest, busiest[1])}",
        "ops_lane_count": len(ops_lanes),
        "lane_busy_us": round(busy_us, 1),
        "lane_span_us": round(span_us, 1),
        "lane_gap_pct": round(gap_pct, 2),
        "category_self_us": {
            k: round(v, 1)
            for k, v in sorted(cats.items(), key=lambda kv: -kv[1])
        },
        "top_ops_self_us": [
            {"op": name[:120], "us": round(dur, 1),
             "pct_of_busy": round(100 * dur / busy_us, 2) if busy_us else 0.0}
            for name, dur in top_ops
        ],
        "invariants": invariants,
    }
