"""Candidate generation: two-tower retrieval + incrementally-fresh ANN.

The subsystem that turns the serving tier from a scorer into a
recommender. Three pieces, each riding an existing contract:

* :mod:`easydl_tpu.retrieval.two_tower` — the model (user/item towers
  over ordinary PS tables, trained from the feedback spool with in-batch
  softmax negatives);
* :mod:`easydl_tpu.retrieval.index` — the ANN index, built by tailing
  the PS push WAL and published as immutable versioned snapshots;
* :mod:`easydl_tpu.retrieval.policy` — the pure rebuild/snapshot
  decisions (rule-5 simulator-replayable).

The request path (``Retrieve`` RPC → frontend index bank → router
session affinity) lives in ``serve/``, next to the ranking path it
feeds.
"""

from easydl_tpu.retrieval.index import AnnIndex, IndexBuilder, brute_force_topk
from easydl_tpu.retrieval.two_tower import (
    TwoTowerTrainer,
    in_batch_softmax_grads,
    pairs_from_events,
    tower_forward,
)

__all__ = [
    "AnnIndex",
    "IndexBuilder",
    "brute_force_topk",
    "TwoTowerTrainer",
    "in_batch_softmax_grads",
    "pairs_from_events",
    "tower_forward",
]
