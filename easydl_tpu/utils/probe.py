"""Bounded, out-of-process JAX backend probing.

The attached TPU arrives via a tunnel that has two distinct failure modes:
it can *error* ("Unable to initialize backend") or it can *hang* — accept
the connection and never return from ``jax.devices()``. An in-process
probe that catches only ``RuntimeError`` survives the first mode and is
killed by the driver's outer timeout on the second, losing the round's
evidence artifacts with it (the round-4 failure: both ``BENCH_r04.json``
and ``MULTICHIP_r04.json`` red for exactly this reason).

The rule these helpers enforce: **evidence entrypoints never initialise
JAX in their own process.** The backend is probed in a subprocess bounded
by a wall-clock timeout; a hang becomes a kill + a structured "unreachable"
answer instead of a lost artifact. (Reference anchor: the capability the
design premises everything on is monitoring a training job,
/root/reference/README.md:21-23 — the measurement pipeline must survive
its own environment.)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

# One python -c line: prints a single JSON object describing the default
# backend. Runs under the ambient environment, so on the build image it
# attaches to whatever the sitecustomize pins (the TPU tunnel) — which is
# the point: the *subprocess* takes the hang risk, not the caller.
_PROBE_SNIPPET = (
    "import json, jax; d = jax.devices(); "
    "print(json.dumps({'platform': jax.default_backend(), "
    "'n_devices': jax.device_count(), "
    "'device_kind': d[0].device_kind}))"
)


def last_json_line(stdout: str, required_key: str) -> Optional[Dict[str, object]]:
    """Last JSON-object line of a child's stdout carrying ``required_key``,
    or None. The one scan both the probe and the bench orchestrator use to
    pick a child's result out of whatever logging surrounds it."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                record = json.loads(line)
            except ValueError:
                return None
            if isinstance(record, dict) and required_key in record:
                return record
    return None


def probe_backend(
    timeout_s: float = 45.0,
    env: Optional[Dict[str, str]] = None,
    python: Optional[str] = None,
) -> Optional[Dict[str, object]]:
    """Probe the default JAX backend in a subprocess, bounded by wall clock.

    Returns ``{"platform", "n_devices", "device_kind"}`` on success, else
    ``None`` (timeout, crash, or unparseable output). Never imports jax in
    the calling process.
    """
    try:
        proc = subprocess.run(
            [python or sys.executable, "-c", _PROBE_SNIPPET],
            env=dict(env) if env is not None else None,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None
    except OSError:
        return None
    if proc.returncode != 0:
        return None
    return last_json_line(proc.stdout, "platform")


def probe_backend_with_retry(
    attempts: int = 4,
    timeout_s: float = 45.0,
    backoff_s: float = 60.0,
    env: Optional[Dict[str, str]] = None,
    python: Optional[str] = None,
) -> Tuple[Optional[Dict[str, object]], List[str]]:
    """Retry :func:`probe_backend` with a fixed backoff between attempts.

    Defaults bound the whole thing at ~4×45s + 3×60s ≈ 6 minutes — long
    enough to ride out a transient tunnel blip, short enough that the
    driver's artifact timeout is never the thing that fires. Returns
    ``(info_or_None, history)`` where history is one human-readable line
    per attempt, for the structured failure JSON.
    """
    history: List[str] = []
    info = None
    for attempt in range(max(1, attempts)):
        t0 = time.monotonic()
        info = probe_backend(timeout_s=timeout_s, env=env, python=python)
        dt = time.monotonic() - t0
        if info is not None:
            history.append(
                f"attempt {attempt + 1}: ok in {dt:.1f}s "
                f"({info.get('platform')}, {info.get('n_devices')} dev)"
            )
            return info, history
        history.append(f"attempt {attempt + 1}: unreachable after {dt:.1f}s")
        if attempt + 1 < attempts:
            time.sleep(backoff_s)
    return None, history


def env_float(name: str, default: float) -> float:
    """Float env-var override with a default (shared by the evidence
    entrypoints' tunable probe/timeout knobs)."""
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ[name])
    except (KeyError, ValueError):
        return default
