"""Host-side input pipeline feeding device buffers.

The reference leaves data loading entirely unspecified (SURVEY.md §3.4); on
TPU the pattern that matters is: each *process* produces its local slice of the
global batch as numpy, ``jax.make_array_from_process_local_data`` assembles the
global sharded array, and a small prefetch queue overlaps host step N+1 with
device step N.

Includes the synthetic datasets the five BASELINE configs need (image/MNIST,
LM token streams, recommender click logs) so benchmarks run hermetically.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from easydl_tpu.core.mesh import batch_divisor


@dataclass
class DataSpec:
    """Shapes/dtypes of one global batch (leaf name → (shape, dtype))."""

    global_batch: int
    leaves: Dict[str, Any]


class SyntheticImages:
    """Deterministic synthetic image classification stream (MNIST/ImageNet
    stand-in: the BASELINE configs 1-2)."""

    def __init__(self, global_batch: int, shape=(28, 28, 1), classes: int = 10, seed: int = 0):
        self.global_batch = global_batch
        self.shape = shape
        self.classes = classes
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield {
                "image": self._rng.standard_normal(
                    (self.global_batch, *self.shape), dtype=np.float32
                ),
                "label": self._rng.integers(
                    0, self.classes, (self.global_batch,), dtype=np.int32
                ),
            }


class SyntheticTokens:
    """LM token stream (BERT/GPT configs 3-4)."""

    def __init__(self, global_batch: int, seq_len: int, vocab: int = 32000, seed: int = 0):
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.vocab = vocab
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            tokens = self._rng.integers(
                0, self.vocab, (self.global_batch, self.seq_len + 1), dtype=np.int32
            )
            yield {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}


class SyntheticClicks:
    """Recommender click log: sparse categorical ids + dense features + label
    (DeepFM/Wide&Deep, BASELINE config 5)."""

    def __init__(
        self,
        global_batch: int,
        num_sparse: int = 26,
        num_dense: int = 13,
        vocab: int = 1_000_000,
        seed: int = 0,
    ):
        self.global_batch = global_batch
        self.num_sparse = num_sparse
        self.num_dense = num_dense
        self.vocab = vocab
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        # Labels carry deterministic structure so training has signal: each
        # sparse id contributes a fixed hash-derived weight and dense features
        # a fixed linear term — embeddings can memorise per-id weights and the
        # dense tower the linear part. (Pure-noise labels would make every
        # learns-something test and the config-5 bench meaningless.)
        from easydl_tpu.ps.table import splitmix64

        dense_w = np.linspace(-1.0, 1.0, self.num_dense).astype(np.float32)
        while True:
            ids = self._rng.integers(
                0, self.vocab, (self.global_batch, self.num_sparse), dtype=np.int64
            )
            dense = self._rng.standard_normal(
                (self.global_batch, self.num_dense), dtype=np.float32
            )
            id_w = (
                (splitmix64(ids) >> np.uint64(40)).astype(np.float32)
                / np.float32(16777216.0)
            ) * 2.0 - 1.0  # per-id fixed weight in [-1, 1)
            score = id_w.mean(axis=1) + 0.5 * (dense @ dense_w) / self.num_dense
            label = (score > 0).astype(np.float32)
            yield {"sparse_ids": ids, "dense": dense, "label": label}


class ShardedLoader:
    """Wraps a host-batch iterator; yields global device arrays batch-sharded
    over the mesh's dp axes, with background prefetch.

    The iterator must yield the full global batch per process in
    single-process mode, or the per-process slice under multi-process JAX —
    ``make_array_from_process_local_data`` handles both.
    """

    def __init__(
        self,
        source: Any,
        mesh,
        sharding=None,
        prefetch: int = 2,
        transform: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
    ):
        from easydl_tpu.core import sharding as shd

        self.mesh = mesh
        self.sharding = sharding if sharding is not None else shd.batch_sharding(mesh)
        self._source = iter(source)
        self._transform = transform
        self._prefetch = max(prefetch, 0)
        self._queue: "queue.Queue" = queue.Queue(maxsize=self._prefetch or 1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        gb = getattr(source, "global_batch", None)
        if gb is not None:
            div = batch_divisor(mesh)
            if gb % div:
                raise ValueError(
                    f"global_batch={gb} not divisible by mesh batch ways={div}"
                )

    def _device_put(self, host_batch: Dict[str, np.ndarray]) -> Any:
        if self._transform:
            host_batch = self._transform(host_batch)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(self.sharding, x),
            host_batch,
        )

    def _worker(self) -> None:
        try:
            for host_batch in self._source:
                if self._stop.is_set():
                    return
                self._queue.put(self._device_put(host_batch))
        finally:
            self._queue.put(None)  # sentinel: source exhausted

    def __iter__(self) -> Iterator[Any]:
        if self._prefetch == 0:
            for host_batch in self._source:
                yield self._device_put(host_batch)
            return
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        while True:
            item = self._queue.get()
            if item is None:
                return
            yield item

    def close(self) -> None:
        self._stop.set()
        # Drain so the worker's blocked put() can observe the stop flag.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
